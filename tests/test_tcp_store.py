"""Native TCPStore tests: the C++ server compiles, serves KV over real
sockets, counts atomically under concurrency, blocks on wait, and runs the
rendezvous barrier across processes (the reference's subprocess pattern)."""
import multiprocessing as mp
import threading
import time

import pytest

from paddle_tpu.distributed import TCPStore
from paddle_tpu.distributed.tcp_store import barrier_via_store


@pytest.fixture(scope="module")
def master():
    store = TCPStore(is_master=True, world_size=1)
    yield store


class TestKV:
    def test_set_get_roundtrip(self, master):
        master.set("alpha", b"hello")
        assert master.get("alpha") == b"hello"

    def test_get_missing_returns_none(self, master):
        assert master.get("nope") is None

    def test_overwrite(self, master):
        master.set("k", "1")
        master.set("k", "2")
        assert master.get("k") == b"2"

    def test_delete(self, master):
        master.set("gone", "x")
        assert master.delete_key("gone")
        assert master.get("gone") is None
        assert not master.delete_key("gone")

    def test_add_counter(self, master):
        assert master.add("cnt", 5) == 5
        assert master.add("cnt", 3) == 8

    def test_second_client_sees_master_data(self, master):
        master.set("shared", b"payload")
        client = TCPStore(host="127.0.0.1", port=master.port)
        assert client.get("shared") == b"payload"

    def test_concurrent_adds_are_atomic(self, master):
        def bump():
            c = TCPStore(host="127.0.0.1", port=master.port)
            for _ in range(50):
                c.add("atomic", 1)
        threads = [threading.Thread(target=bump) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert master.add("atomic", 0) == 200

    def test_wait_blocks_until_set(self, master):
        result = {}

        def waiter():
            c = TCPStore(host="127.0.0.1", port=master.port)
            result["v"] = c.wait("late_key")
        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.2)
        assert "v" not in result  # still blocked
        master.set("late_key", b"now")
        t.join(timeout=5)
        assert result["v"] == b"now"


def _worker(port, rank, world, q):
    store = TCPStore(host="127.0.0.1", port=port)
    store.set(f"rank{rank}", str(rank))
    barrier_via_store(store, "init", world)
    # after the barrier every rank's key must be visible
    vals = sorted(int(store.get(f"rank{r}")) for r in range(world))
    q.put((rank, vals))


class TestRendezvous:
    def test_multiprocess_barrier(self):
        master = TCPStore(is_master=True, world_size=4)
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        procs = [ctx.Process(target=_worker,
                             args=(master.port, r, 4, q))
                 for r in range(4)]
        for p in procs:
            p.start()
        # each spawned worker pays a full jax import (~10s cold); under
        # whole-suite CPU load 60s has proven flaky
        results = [q.get(timeout=240) for _ in range(4)]
        for p in procs:
            p.join(timeout=30)
        for rank, vals in results:
            assert vals == [0, 1, 2, 3]


class TestLauncher:
    def test_launch_spawns_and_injects_env(self, tmp_path):
        script = tmp_path / "trainer.py"
        script.write_text(
            "import os, sys\n"
            "rank = os.environ['PADDLE_TRAINER_ID']\n"
            "n = os.environ['PADDLE_TRAINERS_NUM']\n"
            "print(f'rank {rank}/{n}')\n"
            "sys.exit(0)\n")
        from paddle_tpu.distributed.launch import launch
        rc = launch(str(script), nproc_per_node=2,
                    log_dir=str(tmp_path / "logs"))
        assert rc == 0
        logs = sorted((tmp_path / "logs").iterdir())
        assert len(logs) == 2
        assert "rank 0/2" in logs[0].read_text()

    def test_launch_restarts_on_failure(self, tmp_path):
        marker = tmp_path / "attempt"
        script = tmp_path / "flaky.py"
        script.write_text(
            f"import os, sys\n"
            f"m = {str(marker)!r}\n"
            "if not os.path.exists(m):\n"
            "    open(m, 'w').write('1')\n"
            "    sys.exit(1)\n"  # first attempt fails
            "sys.exit(0)\n")
        from paddle_tpu.distributed.launch import launch
        rc = launch(str(script), nproc_per_node=1, max_restarts=1)
        assert rc == 0
        assert marker.exists()

    def test_elastic_detects_dead_rank(self):
        from paddle_tpu.distributed import TCPStore
        from paddle_tpu.distributed.launch import ElasticManager
        store = TCPStore(is_master=True)
        m0 = ElasticManager(store, rank=0, world_size=2,
                            heartbeat_interval=0.1,
                            heartbeat_timeout=0.5).start()
        # rank 1 never heartbeats -> reported dead; rank 0 alive
        time.sleep(0.3)
        dead = m0.dead_ranks()
        assert dead == [1]
        m0.stop()


class TestRobustness:
    """Regressions: wait timeout, oversized values, prefix delete, shared
    store across threads, clean server shutdown with a blocked waiter."""

    def test_wait_timeout_raises(self, master):
        t0 = time.monotonic()
        with pytest.raises(TimeoutError):
            master.wait("never_set_key", timeout=0.3)
        assert time.monotonic() - t0 < 5

    def test_wait_with_timeout_returns_value_when_set(self, master):
        master.set("tmo_key", b"v")
        assert master.wait("tmo_key", timeout=5) == b"v"

    def test_large_value_roundtrip(self, master):
        # > the 64 KiB first-try client buffer AND > the old 1 MiB cap
        blob = bytes(range(256)) * (9 * 1024)  # 2.25 MiB
        master.set("big", blob)
        assert master.get("big") == blob
        assert master.wait("big", timeout=5) == blob

    def test_delete_prefix(self, master):
        for i in range(5):
            master.set(f"pfx/{i}", str(i))
        master.set("pfx_other", "keep")
        assert master.delete_prefix("pfx/") == 5
        assert master.get("pfx/0") is None
        assert master.get("pfx_other") == b"keep"

    def test_shared_store_across_threads(self, master):
        # one TCPStore object used concurrently from many threads (the
        # ElasticManager heartbeat pattern) — per-thread sockets must not
        # interleave wire bytes
        store = TCPStore(host="127.0.0.1", port=master.port)
        errors = []

        def hammer(tid):
            try:
                for i in range(100):
                    store.set(f"thr/{tid}", f"{tid}:{i}")
                    v = store.get(f"thr/{tid}")
                    assert v is not None and v.decode().startswith(f"{tid}:")
                    store.add("thr_cnt", 1)
            except Exception as e:  # pragma: no cover
                errors.append(e)
        threads = [threading.Thread(target=hammer, args=(t,))
                   for t in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert store.add("thr_cnt", 0) == 400

    def test_server_stop_with_blocked_waiter(self):
        # destroying the master while a client is parked in WAIT must not
        # crash/UAF; the waiter gets an error, not garbage
        srv = TCPStore(is_master=True)
        port = srv.port
        out = {}

        def waiter():
            c = TCPStore(host="127.0.0.1", port=port)
            try:
                c.wait("never")
            except Exception as e:
                out["err"] = e
        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.2)
        del srv  # joins client threads, wakes the waiter
        t.join(timeout=10)
        assert not t.is_alive()
        assert "err" in out


def test_multinode_elastic_restart(tmp_path):
    """Two launchers (one per 'node') share one store; node 1's trainer
    fails on epoch 0 — the epoch counter must restart BOTH nodes, and the
    epoch-namespaced barrier must synchronize all 4 trainers on retry."""
    from paddle_tpu.distributed import TCPStore
    from paddle_tpu.distributed.launch import launch

    import os as _os
    repo = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    script = tmp_path / "train.py"
    script.write_text(
        "import os, sys\n"
        f"sys.path.insert(0, {repo!r})\n"
        "from paddle_tpu.distributed import TCPStore\n"
        "from paddle_tpu.distributed.tcp_store import barrier_via_store\n"
        "rank = int(os.environ['PADDLE_TRAINER_ID'])\n"
        "world = int(os.environ['PADDLE_TRAINERS_NUM'])\n"
        "epoch = os.environ['PADDLE_RESTART_EPOCH']\n"
        "host, port = os.environ['PADDLE_MASTER'].rsplit(':', 1)\n"
        "s = TCPStore(host=host, port=int(port))\n"
        "s.set(f'reg/{epoch}/{rank}', '1')\n"
        "barrier_via_store(s, 'init', world)\n"
        "missing = [r for r in range(world)"
        " if s.get(f'reg/{epoch}/{r}') is None]\n"
        "assert not missing, f'epoch {epoch}: missing {missing}'\n"
        "sys.exit(1 if (epoch == '0' and rank == 3) else 0)\n")

    # reserve an ephemeral port, then let node 0's launcher host the store
    probe = TCPStore(is_master=True)
    port = probe.port
    del probe
    addr = f"127.0.0.1:{port}"
    results = {}

    def run_node(nr):
        results[nr] = launch(str(script), nproc_per_node=2, master=addr,
                             node_rank=nr, nnodes=2, max_restarts=2)

    threads = [threading.Thread(target=run_node, args=(nr,))
               for nr in (1, 0)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert results == {0: 0, 1: 0}, results
