"""jit.save/load inference-export tests: artifact round-trip, parity with
the live model, fresh-process isolation via file reload, InputSpec."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.static import InputSpec


class TestInputSpec:
    def test_basic(self):
        spec = InputSpec([None, 8], "float32", name="x")
        assert spec.shape == (-1, 8)
        s = spec.to_shape_dtype_struct(batch=4)
        assert s.shape == (4, 8)

    def test_from_tensor(self):
        t = pt.to_tensor(np.zeros((2, 3), np.float32))
        spec = InputSpec.from_tensor(t)
        assert spec.shape == (2, 3)


class TestSaveLoad:
    def test_layer_roundtrip(self, tmp_path):
        pt.seed(0)
        m = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 4))
        path = str(tmp_path / "infer")
        pt.jit.save(m, path, input_spec=[InputSpec([4, 8], "float32")])

        loaded = pt.jit.load(path)
        x = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        got = loaded(pt.to_tensor(x)).numpy()
        ref = m(pt.to_tensor(x)).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_params_are_baked(self, tmp_path):
        pt.seed(1)
        m = nn.Linear(4, 2)
        path = str(tmp_path / "baked")
        pt.jit.save(m, path, input_spec=[InputSpec([2, 4], "float32")])
        loaded = pt.jit.load(path)
        x = np.ones((2, 4), np.float32)
        before = loaded(pt.to_tensor(x)).numpy()
        m.weight.set_value(m.weight.numpy() * 0)  # mutate live model
        after = loaded(pt.to_tensor(x)).numpy()
        np.testing.assert_allclose(before, after)  # artifact unaffected

    def test_pdiparams_written(self, tmp_path):
        m = nn.Linear(4, 2)
        path = str(tmp_path / "withparams")
        pt.jit.save(m, path, input_spec=[InputSpec([1, 4], "float32")])
        sd = pt.load(path + ".pdiparams")
        np.testing.assert_allclose(sd["weight"].numpy(), m.weight.numpy())

    def test_transformer_export(self, tmp_path):
        pt.seed(2)
        enc = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
        enc.eval()
        path = str(tmp_path / "enc")
        pt.jit.save(enc, path, input_spec=[InputSpec([2, 6, 16], "float32")])
        loaded = pt.jit.load(path)
        x = np.random.RandomState(3).randn(2, 6, 16).astype(np.float32)
        np.testing.assert_allclose(loaded(pt.to_tensor(x)).numpy(),
                                   enc(pt.to_tensor(x)).numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_missing_spec_raises(self, tmp_path):
        with pytest.raises(ValueError):
            pt.jit.save(nn.Linear(2, 2), str(tmp_path / "x"))


def test_llama_export_predictor_roundtrip(tmp_path):
    """Deployment story for the flagship model: jit.save -> jit.load and
    inference.Predictor reproduce eager logits. (Symbolic batch dims are
    not supported through XLA export for the attention path — export with
    static shapes.)"""
    import os
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.static import InputSpec

    pt.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=1))
    m.eval()
    ids = np.random.RandomState(0).randint(0, 256, (2, 16)).astype(np.int32)
    want = np.asarray(m(pt.to_tensor(ids)).data)
    path = os.path.join(tmp_path, "llama_export")
    pt.jit.save(m, path, input_spec=[InputSpec([2, 16], "int32")])
    got = np.asarray(pt.jit.load(path)(pt.to_tensor(ids)).data)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    from paddle_tpu.inference import Config, Predictor
    out = Predictor(Config(path)).run([ids])
    np.testing.assert_allclose(np.asarray(out[0]), want, rtol=1e-4,
                               atol=1e-5)


class TestSymbolicBatchExport:
    """Dynamic-batch export (reference: -1 dims in paddle's input_spec;
    round-2 limitation 'static shapes only' removed — shape-polymorphic
    StableHLO now serves any batch size through the attention path)."""

    def test_llama_dynamic_batch(self, tmp_path):
        import paddle_tpu as pt
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.static import InputSpec

        cfg = LlamaConfig(vocab_size=64, hidden_size=32,
                          intermediate_size=64, num_hidden_layers=2,
                          num_attention_heads=2, num_key_value_heads=1,
                          max_position_embeddings=64,
                          tie_word_embeddings=True)
        pt.seed(0)
        m = LlamaForCausalLM(cfg)
        m.eval()
        pt.jit.save(m, str(tmp_path / "m"),
                    input_spec=[InputSpec([None, 16], "int64", "ids")])
        back = pt.jit.load(str(tmp_path / "m"))
        for B in (1, 3, 5):
            ids = pt.to_tensor(np.random.RandomState(B).randint(
                0, 64, (B, 16)).astype(np.int64))
            np.testing.assert_allclose(back(ids).numpy(), m(ids).numpy(),
                                       rtol=2e-4, atol=2e-5)

    def test_transformer_encoder_dynamic_batch(self, tmp_path):
        import paddle_tpu as pt
        import paddle_tpu.nn as nn
        from paddle_tpu.static import InputSpec

        pt.seed(1)
        enc = nn.TransformerEncoderLayer(d_model=32, nhead=4,
                                         dim_feedforward=64, dropout=0.0)
        enc.eval()
        pt.jit.save(enc, str(tmp_path / "enc"),
                    input_spec=[InputSpec([None, 12, 32], "float32", "x")])
        back = pt.jit.load(str(tmp_path / "enc"))
        for B in (2, 7):
            x = pt.to_tensor(np.random.RandomState(B).randn(
                B, 12, 32).astype(np.float32))
            np.testing.assert_allclose(back(x).numpy(), enc(x).numpy(),
                                       rtol=2e-4, atol=2e-5)

    def test_dynamic_batch_through_expand_and_zeros(self, tmp_path):
        """expand/broadcast_to/zeros with a batch-derived dim must survive
        symbolic export (reshape alone is not enough)."""
        import paddle_tpu as pt
        import paddle_tpu.nn as nn
        from paddle_tpu import ops
        from paddle_tpu.static import InputSpec

        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(8, 8)

            def forward(self, x):
                B = x.shape[0]
                bias = ops.expand(ops.zeros([1, 8]), [B, 8])
                mask = ops.broadcast_to(ops.ones([1, 8]), [B, 8])
                return self.fc(x) + bias + mask

        pt.seed(2)
        m = M()
        m.eval()
        pt.jit.save(m, str(tmp_path / "m"),
                    input_spec=[InputSpec([None, 8], "float32", "x")])
        back = pt.jit.load(str(tmp_path / "m"))
        for B in (1, 4):
            x = pt.to_tensor(np.random.RandomState(B).randn(
                B, 8).astype(np.float32))
            np.testing.assert_allclose(back(x).numpy(), m(x).numpy(),
                                       rtol=2e-4, atol=2e-5)


def test_reshape_zero_copies_input_dim():
    """paddle semantics: 0 in a reshape target copies the input dim."""
    import paddle_tpu as pt
    from paddle_tpu import ops
    x = pt.to_tensor(np.arange(24, dtype=np.float32).reshape(4, 6))
    assert ops.reshape(x, [0, -1]).shape == [4, 6]
    assert ops.reshape(x, [0, 2, 3]).shape == [4, 2, 3]
