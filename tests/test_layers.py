"""Layer zoo tests: shape/grad checks per family, torch oracles for the
stateful layers (RNN/BatchNorm), and an end-to-end transformer LM train."""
import numpy as np
import pytest
import torch

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def t(x):
    return pt.to_tensor(np.asarray(x, dtype=np.float32))


class TestCommon:
    def test_linear_matches_manual(self):
        m = nn.Linear(4, 3)
        x = np.random.RandomState(0).randn(5, 4).astype(np.float32)
        got = m(t(x)).numpy()
        ref = x @ m.weight.numpy() + m.bias.numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-5)

    def test_linear_no_bias(self):
        m = nn.Linear(4, 3, bias_attr=False)
        assert m.bias is None

    def test_embedding_padding_idx(self):
        m = nn.Embedding(10, 4, padding_idx=0)
        assert np.all(m.weight.numpy()[0] == 0)
        out = m(pt.to_tensor(np.array([[0, 3]], dtype=np.int64)))
        assert np.all(out.numpy()[0, 0] == 0)

    def test_flatten(self):
        m = nn.Flatten()
        out = m(t(np.zeros((2, 3, 4))))
        assert out.shape == [2, 12]

    def test_dropout_train_eval(self):
        m = nn.Dropout(0.5)
        x = t(np.ones((100, 100)))
        m.eval()
        np.testing.assert_allclose(m(x).numpy(), 1.0)
        m.train()
        y = m(x).numpy()
        assert (y == 0).any() and not (y == 0).all()

    def test_pad2d(self):
        m = nn.Pad2D([1, 2, 3, 4])
        out = m(t(np.zeros((1, 1, 5, 5))))
        assert out.shape == [1, 1, 12, 8]

    def test_upsample(self):
        m = nn.Upsample(scale_factor=2, mode="nearest")
        out = m(t(np.ones((1, 1, 3, 3))))
        assert out.shape == [1, 1, 6, 6]

    def test_identity(self):
        x = t([1.0, 2.0])
        assert nn.Identity()(x) is x


class TestActivationLayers:
    @pytest.mark.parametrize("cls,fn", [
        (nn.ReLU, F.relu), (nn.GELU, F.gelu), (nn.Sigmoid, F.sigmoid),
        (nn.Tanh, F.tanh), (nn.Silu, F.silu), (nn.Hardswish, F.hardswish),
        (nn.Softplus, F.softplus), (nn.Mish, F.mish), (nn.ELU, F.elu),
    ])
    def test_matches_functional(self, cls, fn):
        x = t(np.random.RandomState(0).randn(3, 4))
        np.testing.assert_allclose(cls()(x).numpy(), fn(x).numpy(), rtol=1e-6)

    def test_softmax_axis(self):
        x = t(np.random.RandomState(0).randn(2, 5))
        out = nn.Softmax()(x).numpy()
        np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5)

    def test_prelu_learnable(self):
        m = nn.PReLU(num_parameters=1, init=0.3)
        x = t([[-2.0, 4.0]])
        np.testing.assert_allclose(m(x).numpy(), [[-0.6, 4.0]], rtol=1e-5)
        (m(x).sum()).backward()
        assert m.weight.grad is not None


class TestConvLayers:
    def test_conv2d_matches_torch(self):
        rng = np.random.RandomState(0)
        m = nn.Conv2D(3, 8, 3, stride=2, padding=1)
        x = rng.randn(2, 3, 8, 8).astype(np.float32)
        got = m(t(x)).numpy()
        ref = torch.nn.functional.conv2d(
            torch.tensor(x), torch.tensor(m.weight.numpy()),
            torch.tensor(m.bias.numpy()), stride=2, padding=1).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_conv2d_transpose_matches_torch(self):
        rng = np.random.RandomState(1)
        m = nn.Conv2DTranspose(4, 6, 3, stride=2, padding=1)
        x = rng.randn(2, 4, 5, 5).astype(np.float32)
        got = m(t(x)).numpy()
        ref = torch.nn.functional.conv_transpose2d(
            torch.tensor(x), torch.tensor(m.weight.numpy()),
            torch.tensor(m.bias.numpy()), stride=2, padding=1).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_conv1d_grouped(self):
        m = nn.Conv1D(4, 8, 3, groups=2)
        out = m(t(np.random.randn(1, 4, 10)))
        assert out.shape == [1, 8, 8]


class TestNormLayers:
    def test_layer_norm_matches_torch(self):
        rng = np.random.RandomState(0)
        m = nn.LayerNorm(6)
        x = rng.randn(4, 6).astype(np.float32)
        ref = torch.nn.functional.layer_norm(
            torch.tensor(x), (6,), torch.tensor(m.weight.numpy()),
            torch.tensor(m.bias.numpy())).numpy()
        np.testing.assert_allclose(m(t(x)).numpy(), ref, rtol=1e-4,
                                   atol=1e-5)

    def test_rms_norm(self):
        m = nn.RMSNorm(8)
        x = np.random.RandomState(0).randn(2, 8).astype(np.float32)
        got = m(t(x)).numpy()
        ref = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6)
        np.testing.assert_allclose(got, ref, rtol=1e-4)

    def test_batchnorm_updates_running_stats(self):
        m = nn.BatchNorm2D(3)
        x = t(np.random.RandomState(0).randn(4, 3, 5, 5) * 2 + 1)
        before = m._mean.numpy().copy()
        m.train()
        m(x)
        after = m._mean.numpy()
        assert not np.allclose(before, after)

    def test_batchnorm_eval_matches_torch(self):
        rng = np.random.RandomState(0)
        x = rng.randn(4, 3, 5, 5).astype(np.float32)
        m = nn.BatchNorm2D(3)
        m.eval()
        tm = torch.nn.BatchNorm2d(3).eval()
        got = m(t(x)).numpy()
        ref = tm(torch.tensor(x)).detach().numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_group_norm_matches_torch(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 6, 4, 4).astype(np.float32)
        m = nn.GroupNorm(3, 6)
        ref = torch.nn.functional.group_norm(
            torch.tensor(x), 3, torch.tensor(m.weight.numpy()),
            torch.tensor(m.bias.numpy())).numpy()
        np.testing.assert_allclose(m(t(x)).numpy(), ref, rtol=1e-4,
                                   atol=1e-5)

    def test_sync_batchnorm_convert(self):
        model = nn.Sequential(nn.Conv2D(3, 4, 3), nn.BatchNorm2D(4))
        converted = nn.SyncBatchNorm.convert_sync_batchnorm(model)
        assert isinstance(converted._sub_layers["1"], nn.SyncBatchNorm)


class TestPoolingLayers:
    def test_maxpool_layer(self):
        m = nn.MaxPool2D(2)
        out = m(t(np.random.randn(1, 1, 4, 4)))
        assert out.shape == [1, 1, 2, 2]

    def test_adaptive_avg_nondivisible(self):
        m = nn.AdaptiveAvgPool2D((3, 3))
        x = np.random.RandomState(0).randn(1, 2, 7, 7).astype(np.float32)
        ref = torch.nn.functional.adaptive_avg_pool2d(
            torch.tensor(x), (3, 3)).numpy()
        np.testing.assert_allclose(m(t(x)).numpy(), ref, rtol=1e-5,
                                   atol=1e-6)


class TestLossLayers:
    def test_cross_entropy_layer(self):
        logits = t(np.random.RandomState(0).randn(4, 10))
        labels = pt.to_tensor(np.array([1, 3, 5, 7], dtype=np.int64))
        loss = nn.CrossEntropyLoss()(logits, labels)
        ref = torch.nn.functional.cross_entropy(
            torch.tensor(logits.numpy()), torch.tensor(labels.numpy()).long())
        np.testing.assert_allclose(float(loss.numpy()), float(ref),
                                   rtol=1e-5)

    def test_mse_layer(self):
        a, b = t([1.0, 2.0]), t([0.0, 0.0])
        np.testing.assert_allclose(float(nn.MSELoss()(a, b).numpy()), 2.5)

    def test_bce_with_logits(self):
        x = t(np.random.RandomState(0).randn(8))
        y = t((np.random.RandomState(1).rand(8) > 0.5).astype(np.float32))
        got = nn.BCEWithLogitsLoss()(x, y)
        ref = torch.nn.functional.binary_cross_entropy_with_logits(
            torch.tensor(x.numpy()), torch.tensor(y.numpy()))
        np.testing.assert_allclose(float(got.numpy()), float(ref), rtol=1e-5)

    def test_smooth_l1(self):
        x = t(np.random.RandomState(0).randn(8))
        y = t(np.random.RandomState(1).randn(8))
        got = nn.SmoothL1Loss()(x, y)
        ref = torch.nn.functional.smooth_l1_loss(
            torch.tensor(x.numpy()), torch.tensor(y.numpy()))
        np.testing.assert_allclose(float(got.numpy()), float(ref), rtol=1e-5)


class TestReviewRegressions:
    def test_soft_margin_loss_reductions(self):
        x = t(np.random.RandomState(0).randn(8))
        y = t(np.sign(np.random.RandomState(1).randn(8)))
        for red in ("mean", "sum", "none"):
            got = nn.SoftMarginLoss(reduction=red)(x, y)
            ref = torch.nn.functional.soft_margin_loss(
                torch.tensor(x.numpy()), torch.tensor(y.numpy()),
                reduction=red)
            np.testing.assert_allclose(np.asarray(got.numpy()),
                                       ref.numpy(), rtol=1e-5)

    def test_weight_norm_roundtrip_dim1(self):
        from paddle_tpu.nn.utils import weight_norm, remove_weight_norm
        m = nn.Linear(4, 3)
        before = m.weight.numpy().copy()
        weight_norm(m, dim=1)
        remove_weight_norm(m)
        np.testing.assert_allclose(m.weight.numpy(), before, rtol=1e-5,
                                   atol=1e-6)

    def test_weight_norm_forward_consistent(self):
        from paddle_tpu.nn.utils import weight_norm
        m = nn.Linear(4, 3)
        x = t(np.random.RandomState(0).randn(2, 4))
        before = m(x).numpy()
        weight_norm(m)
        np.testing.assert_allclose(m(x).numpy(), before, rtol=1e-5,
                                   atol=1e-6)
        (m(x).sum()).backward()
        assert m.weight_g.grad is not None and m.weight_v.grad is not None

    def test_spectral_norm_converges(self):
        m = nn.SpectralNorm([6, 4], power_iters=1)
        w = t(np.random.RandomState(0).randn(6, 4))
        u_before = m.weight_u.numpy().copy()
        m(w)
        assert not np.allclose(m.weight_u.numpy(), u_before)
        for _ in range(50):
            out = m(w)
        # converged sigma: largest singular value of normalized output ~= 1
        s = np.linalg.svd(out.numpy(), compute_uv=False)[0]
        np.testing.assert_allclose(s, 1.0, rtol=1e-3)

    def test_return_mask_raises(self):
        with pytest.raises(NotImplementedError):
            F.adaptive_max_pool2d(t(np.zeros((1, 1, 4, 4))), 2,
                                  return_mask=True)
        with pytest.raises(NotImplementedError):
            nn.MaxPool2D(2, return_mask=True)

    def test_multiplicative_decay_stable_and_jumpable(self):
        import paddle_tpu.optimizer as opt
        s = opt.lr.MultiplicativeDecay(0.1, lambda e: 0.5)
        s.step()
        v1 = s.get_lr()
        assert s.get_lr() == v1  # repeated calls do not drift
        s.step()
        assert abs(s.get_lr() - 0.1 * 0.25) < 1e-12
        s.step(epoch=1)  # backward jump recomposes
        assert abs(s.get_lr() - 0.05) < 1e-12


class TestRNN:
    def test_lstm_matches_torch(self):
        rng = np.random.RandomState(0)
        B, T, I, H = 2, 5, 4, 6
        m = nn.LSTM(I, H)
        tm = torch.nn.LSTM(I, H, batch_first=True)
        # copy our weights into torch (same [4H, I] layout; gate order i,f,c,o
        # in paddle vs i,f,g,o in torch — identical meaning)
        sd = {
            "weight_ih_l0": torch.tensor(m._cells[0].weight_ih.numpy()),
            "weight_hh_l0": torch.tensor(m._cells[0].weight_hh.numpy()),
            "bias_ih_l0": torch.tensor(m._cells[0].bias_ih.numpy()),
            "bias_hh_l0": torch.tensor(m._cells[0].bias_hh.numpy()),
        }
        tm.load_state_dict(sd)
        x = rng.randn(B, T, I).astype(np.float32)
        out, (h, c) = m(t(x))
        tout, (th, tc) = tm(torch.tensor(x))
        np.testing.assert_allclose(out.numpy(), tout.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(h.numpy(), th.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(c.numpy(), tc.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_gru_matches_torch(self):
        rng = np.random.RandomState(0)
        B, T, I, H = 2, 4, 3, 5
        m = nn.GRU(I, H)
        tm = torch.nn.GRU(I, H, batch_first=True)
        sd = {
            "weight_ih_l0": torch.tensor(m._cells[0].weight_ih.numpy()),
            "weight_hh_l0": torch.tensor(m._cells[0].weight_hh.numpy()),
            "bias_ih_l0": torch.tensor(m._cells[0].bias_ih.numpy()),
            "bias_hh_l0": torch.tensor(m._cells[0].bias_hh.numpy()),
        }
        tm.load_state_dict(sd)
        x = rng.randn(B, T, I).astype(np.float32)
        out, h = m(t(x))
        tout, th = tm(torch.tensor(x))
        np.testing.assert_allclose(out.numpy(), tout.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_lstm_sequence_length_masks(self):
        m = nn.LSTM(3, 4)
        x = t(np.random.RandomState(0).randn(2, 6, 3))
        out, (h, c) = m(x, sequence_length=pt.to_tensor(
            np.array([6, 3], dtype=np.int32)))
        # outputs past the length are zero for sample 1
        assert np.all(out.numpy()[1, 3:] == 0)
        assert not np.all(out.numpy()[1, :3] == 0)
        # final state of sample 1 equals state at t=3 (run truncated input)
        out2, (h2, _) = m(t(x.numpy()[1:2, :3]))
        np.testing.assert_allclose(h.numpy()[0, 1], h2.numpy()[0, 0],
                                   rtol=1e-4, atol=1e-5)

    def test_simple_rnn_cell_single_step(self):
        cell = nn.SimpleRNNCell(4, 5)
        x = t(np.random.RandomState(0).randn(3, 4))
        out, h = cell(x)
        assert out.shape == [3, 5]
        ref = np.tanh(
            x.numpy() @ cell.weight_ih.numpy().T + cell.bias_ih.numpy() +
            np.zeros((3, 5)) @ cell.weight_hh.numpy().T +
            cell.bias_hh.numpy())
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)

    def test_birnn_wrapper(self):
        fw, bw = nn.GRUCell(3, 4), nn.GRUCell(3, 4)
        m = nn.BiRNN(fw, bw)
        out, (ff, fb) = m(t(np.random.randn(2, 5, 3)))
        assert out.shape == [2, 5, 8]

    def test_rnn_backward_flows(self):
        m = nn.LSTM(3, 4)
        x = t(np.random.RandomState(0).randn(2, 5, 3))
        out, _ = m(x)
        out.mean().backward()
        for p in m.parameters():
            assert p.grad is not None
            assert np.isfinite(p.grad.numpy()).all()


class TestTransformer:
    def test_encoder_layer_shapes_and_grad(self):
        enc = nn.TransformerEncoderLayer(16, 4, 32)
        enc.eval()
        x = t(np.random.RandomState(0).randn(2, 5, 16))
        out = enc(x)
        assert out.shape == [2, 5, 16]
        out.mean().backward()
        assert enc.linear1.weight.grad is not None

    def test_encoder_stack_distinct_layers(self):
        layer = nn.TransformerEncoderLayer(8, 2, 16)
        enc = nn.TransformerEncoder(layer, 3)
        assert len(list(enc.layers)) == 3
        # clones share values initially but are distinct objects
        p0 = enc.layers[0].linear1.weight
        p1 = enc.layers[1].linear1.weight
        assert p0 is not p1
        np.testing.assert_allclose(p0.numpy(), p1.numpy())

    def test_decoder_and_full_transformer(self):
        model = nn.Transformer(d_model=16, nhead=4, num_encoder_layers=2,
                               num_decoder_layers=2, dim_feedforward=32)
        model.eval()
        src = t(np.random.RandomState(0).randn(2, 6, 16))
        tgt = t(np.random.RandomState(1).randn(2, 4, 16))
        mask = model.generate_square_subsequent_mask(4)
        out = model(src, tgt, tgt_mask=mask)
        assert out.shape == [2, 4, 16]

    def test_causal_mask_blocks_future(self):
        mha = nn.MultiHeadAttention(8, 2)
        mha.eval()
        x = np.random.RandomState(0).randn(1, 4, 8).astype(np.float32)
        m = nn.Transformer.generate_square_subsequent_mask(4)
        out1 = mha(t(x), attn_mask=m).numpy()
        x2 = x.copy()
        x2[0, 3] += 100.0  # perturb the last position only
        out2 = mha(t(x2), attn_mask=m).numpy()
        np.testing.assert_allclose(out1[0, :3], out2[0, :3], rtol=1e-4,
                                   atol=1e-5)

    def test_decoder_cache_incremental_matches_full(self):
        dec_layer = nn.TransformerDecoderLayer(8, 2, 16)
        dec = nn.TransformerDecoder(dec_layer, 2)
        dec.eval()
        memory = t(np.random.RandomState(0).randn(1, 5, 8))
        tgt = np.random.RandomState(1).randn(1, 3, 8).astype(np.float32)
        causal = nn.Transformer.generate_square_subsequent_mask(3)
        full = dec(t(tgt), memory, tgt_mask=causal).numpy()
        cache = dec.gen_cache(memory)
        steps = []
        for i in range(3):
            out, cache = dec(t(tgt[:, i:i + 1]), memory, cache=cache)
            steps.append(out.numpy())
        inc = np.concatenate(steps, axis=1)
        np.testing.assert_allclose(full, inc, rtol=1e-4, atol=1e-5)

    def test_tiny_lm_trains(self):
        # end-to-end: embedding -> encoder layer -> vocab head learns to
        # predict a fixed next-token mapping (the VERDICT's "done" bar)
        import paddle_tpu.optimizer as opt
        rng = np.random.RandomState(0)
        V, D, T, B = 17, 16, 6, 8
        emb = nn.Embedding(V, D)
        enc = nn.TransformerEncoderLayer(D, 4, 32, dropout=0.0)
        head = nn.Linear(D, V)
        params = (list(emb.parameters()) + list(enc.parameters()) +
                  list(head.parameters()))
        o = opt.AdamW(learning_rate=5e-3, parameters=params)
        loss_fn = nn.CrossEntropyLoss()
        perm = rng.permutation(V)  # fixed next-token rule
        causal = nn.Transformer.generate_square_subsequent_mask(T)

        losses = []
        for step in range(60):
            toks = rng.randint(0, V, size=(B, T))
            nxt = perm[toks]
            h = enc(emb(pt.to_tensor(toks.astype(np.int64))),
                    src_mask=causal)
            logits = head(h)
            loss = loss_fn(
                pt.reshape(logits, [-1, V]),
                pt.to_tensor(nxt.reshape(-1).astype(np.int64)))
            loss.backward()
            o.step()
            o.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.2, (losses[0], losses[-1])
