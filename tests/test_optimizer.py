"""Optimizer tests: numpy-oracle per update rule (the reference's OpTest
pattern, SURVEY.md §4) + end-to-end convergence through the public API."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.optimizer as opt
from paddle_tpu.nn.clip import (ClipGradByGlobalNorm, ClipGradByNorm,
                                ClipGradByValue)


def _param_with_grad(shape=(4, 3), seed=0):
    rng = np.random.RandomState(seed)
    p = pt.Parameter(rng.randn(*shape).astype(np.float32))
    g = rng.randn(*shape).astype(np.float32)
    p.grad = pt.to_tensor(g)
    return p, g


def _steps(o, p, g, n=3):
    outs = []
    for _ in range(n):
        p.grad = pt.to_tensor(g)
        o.step()
        outs.append(p.numpy().copy())
    return outs


class TestRules:
    def test_sgd(self):
        p, g = _param_with_grad()
        ref = p.numpy().copy()
        o = opt.SGD(learning_rate=0.1, parameters=[p])
        for got in _steps(o, p, g):
            ref = ref - 0.1 * g
            np.testing.assert_allclose(got, ref, rtol=1e-6)

    def test_momentum(self):
        p, g = _param_with_grad()
        ref, v = p.numpy().copy(), np.zeros_like(g)
        o = opt.Momentum(learning_rate=0.1, momentum=0.9, parameters=[p])
        for got in _steps(o, p, g):
            v = 0.9 * v + g
            ref = ref - 0.1 * v
            np.testing.assert_allclose(got, ref, rtol=1e-6)

    def test_momentum_nesterov(self):
        p, g = _param_with_grad()
        ref, v = p.numpy().copy(), np.zeros_like(g)
        o = opt.Momentum(learning_rate=0.1, momentum=0.9, parameters=[p],
                         use_nesterov=True)
        for got in _steps(o, p, g):
            v = 0.9 * v + g
            ref = ref - 0.1 * (g + 0.9 * v)
            np.testing.assert_allclose(got, ref, rtol=1e-6)

    def test_adam(self):
        p, g = _param_with_grad()
        ref = p.numpy().copy()
        m = np.zeros_like(g)
        v = np.zeros_like(g)
        b1, b2, eps, lr = 0.9, 0.999, 1e-8, 0.01
        o = opt.Adam(learning_rate=lr, parameters=[p], epsilon=eps)
        for t, got in enumerate(_steps(o, p, g, n=4), start=1):
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            lr_t = lr * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
            ref = ref - lr_t * m / (np.sqrt(v) + eps)
            np.testing.assert_allclose(got, ref, rtol=1e-5)

    def test_adam_l2_regularization_enters_moments(self):
        p, g = _param_with_grad()
        ref = p.numpy().copy()
        m = np.zeros_like(g)
        v = np.zeros_like(g)
        b1, b2, eps, lr, wd = 0.9, 0.999, 1e-8, 0.01, 0.1
        o = opt.Adam(learning_rate=lr, parameters=[p], weight_decay=wd)
        for t, got in enumerate(_steps(o, p, g, n=3), start=1):
            geff = g + wd * ref
            m = b1 * m + (1 - b1) * geff
            v = b2 * v + (1 - b2) * geff * geff
            lr_t = lr * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
            ref = ref - lr_t * m / (np.sqrt(v) + eps)
            np.testing.assert_allclose(got, ref, rtol=1e-5)

    def test_adamw_decoupled(self):
        p, g = _param_with_grad()
        ref = p.numpy().copy()
        m = np.zeros_like(g)
        v = np.zeros_like(g)
        b1, b2, eps, lr, wd = 0.9, 0.999, 1e-8, 0.01, 0.05
        o = opt.AdamW(learning_rate=lr, parameters=[p], weight_decay=wd)
        for t, got in enumerate(_steps(o, p, g, n=3), start=1):
            m = b1 * m + (1 - b1) * g  # decay never enters moments
            v = b2 * v + (1 - b2) * g * g
            lr_t = lr * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
            ref = ref * (1 - lr * wd) - lr_t * m / (np.sqrt(v) + eps)
            np.testing.assert_allclose(got, ref, rtol=1e-5)

    def test_adamw_apply_decay_param_fun(self):
        p, g = _param_with_grad()
        p.name = "bias"
        ref = p.numpy().copy()
        m = np.zeros_like(g)
        v = np.zeros_like(g)
        b1, b2, eps, lr = 0.9, 0.999, 1e-8, 0.01
        o = opt.AdamW(learning_rate=lr, parameters=[p], weight_decay=0.5,
                      apply_decay_param_fun=lambda n: n != "bias")
        for t, got in enumerate(_steps(o, p, g, n=2), start=1):
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            lr_t = lr * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
            ref = ref - lr_t * m / (np.sqrt(v) + eps)  # no decay on bias
            np.testing.assert_allclose(got, ref, rtol=1e-5)

    def test_adagrad(self):
        p, g = _param_with_grad()
        ref = p.numpy().copy()
        acc = np.zeros_like(g)
        o = opt.Adagrad(learning_rate=0.1, parameters=[p], epsilon=1e-6)
        for got in _steps(o, p, g):
            acc = acc + g * g
            ref = ref - 0.1 * g / (np.sqrt(acc) + 1e-6)
            np.testing.assert_allclose(got, ref, rtol=1e-5)

    def test_rmsprop(self):
        p, g = _param_with_grad()
        ref = p.numpy().copy()
        ms = np.zeros_like(g)
        mom = np.zeros_like(g)
        rho, eps, mu, lr = 0.95, 1e-6, 0.9, 0.01
        o = opt.RMSProp(learning_rate=lr, rho=rho, epsilon=eps, momentum=mu,
                        parameters=[p])
        for got in _steps(o, p, g):
            ms = rho * ms + (1 - rho) * g * g
            mom = mu * mom + lr * g / np.sqrt(ms + eps)
            ref = ref - mom
            np.testing.assert_allclose(got, ref, rtol=1e-5)

    def test_rmsprop_centered(self):
        p, g = _param_with_grad()
        ref = p.numpy().copy()
        ms = np.zeros_like(g)
        mg = np.zeros_like(g)
        mom = np.zeros_like(g)
        rho, eps, lr = 0.95, 1e-6, 0.01
        o = opt.RMSProp(learning_rate=lr, rho=rho, epsilon=eps, centered=True,
                        parameters=[p])
        for got in _steps(o, p, g):
            ms = rho * ms + (1 - rho) * g * g
            mg = rho * mg + (1 - rho) * g
            mom = lr * g / np.sqrt(ms - mg * mg + eps)
            ref = ref - mom
            np.testing.assert_allclose(got, ref, rtol=1e-5)

    def test_adadelta(self):
        p, g = _param_with_grad()
        ref = p.numpy().copy()
        asg = np.zeros_like(g)
        asu = np.zeros_like(g)
        rho, eps = 0.95, 1e-6
        o = opt.Adadelta(parameters=[p], rho=rho, epsilon=eps)
        for got in _steps(o, p, g):
            asg = rho * asg + (1 - rho) * g * g
            upd = -np.sqrt((asu + eps) / (asg + eps)) * g
            asu = rho * asu + (1 - rho) * upd * upd
            ref = ref + upd
            np.testing.assert_allclose(got, ref, rtol=1e-5)

    def test_adamax(self):
        p, g = _param_with_grad()
        ref = p.numpy().copy()
        m = np.zeros_like(g)
        u = np.zeros_like(g)
        b1, b2, eps, lr = 0.9, 0.999, 1e-8, 0.01
        o = opt.Adamax(learning_rate=lr, parameters=[p])
        for t, got in enumerate(_steps(o, p, g), start=1):
            m = b1 * m + (1 - b1) * g
            u = np.maximum(np.abs(g), b2 * u + eps)
            ref = ref - (lr / (1 - b1 ** t)) * m / u
            np.testing.assert_allclose(got, ref, rtol=1e-5)

    def test_lamb(self):
        p, g = _param_with_grad()
        ref = p.numpy().copy()
        m = np.zeros_like(g)
        v = np.zeros_like(g)
        b1, b2, eps, lr, wd = 0.9, 0.999, 1e-6, 0.01, 0.01
        o = opt.Lamb(learning_rate=lr, parameters=[p])
        for t, got in enumerate(_steps(o, p, g), start=1):
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            r = (m / (1 - b1 ** t)) / (np.sqrt(v / (1 - b2 ** t)) + eps) \
                + wd * ref
            ratio = np.linalg.norm(ref) / np.linalg.norm(r)
            ref = ref - lr * ratio * r
            np.testing.assert_allclose(got, ref, rtol=1e-4)


class TestClip:
    def test_by_value(self):
        p, g = _param_with_grad()
        clipped = ClipGradByValue(0.5)([(p, p.grad)])
        np.testing.assert_allclose(clipped[0][1].numpy(),
                                   np.clip(g, -0.5, 0.5), rtol=1e-6)

    def test_by_norm(self):
        p, g = _param_with_grad()
        clipped = ClipGradByNorm(1.0)([(p, p.grad)])
        n = np.linalg.norm(g)
        expect = g / n if n > 1.0 else g
        np.testing.assert_allclose(clipped[0][1].numpy(), expect, rtol=1e-5)

    def test_by_global_norm(self):
        p1, g1 = _param_with_grad(seed=1)
        p2, g2 = _param_with_grad(seed=2)
        clipped = ClipGradByGlobalNorm(1.0)([(p1, p1.grad), (p2, p2.grad)])
        gn = np.sqrt((g1 ** 2).sum() + (g2 ** 2).sum())
        scale = 1.0 / max(gn, 1.0)
        np.testing.assert_allclose(clipped[0][1].numpy(), g1 * scale,
                                   rtol=1e-5)
        np.testing.assert_allclose(clipped[1][1].numpy(), g2 * scale,
                                   rtol=1e-5)

    def test_global_norm_below_threshold_noop(self):
        p, g = _param_with_grad()
        p.grad = pt.to_tensor(g * 1e-3)
        clipped = ClipGradByGlobalNorm(10.0)([(p, p.grad)])
        np.testing.assert_allclose(clipped[0][1].numpy(), g * 1e-3, rtol=1e-6)

    def test_optimizer_with_clip(self):
        p, g = _param_with_grad()
        before = p.numpy().copy()
        o = opt.SGD(learning_rate=1.0, parameters=[p],
                    grad_clip=ClipGradByGlobalNorm(0.1))
        p.grad = pt.to_tensor(g)
        o.step()
        delta = np.linalg.norm(p.numpy() - before)
        assert delta <= 0.1 + 1e-5


class TestLRSchedulers:
    def test_step_decay(self):
        s = opt.lr.StepDecay(learning_rate=0.1, step_size=2, gamma=0.5)
        lrs = [s()]
        for _ in range(4):
            s.step()
            lrs.append(s())
        np.testing.assert_allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025],
                                   rtol=1e-6)

    def test_multistep(self):
        s = opt.lr.MultiStepDecay(0.1, milestones=[2, 4], gamma=0.1)
        got = []
        for _ in range(5):
            got.append(s())
            s.step()
        np.testing.assert_allclose(got, [0.1, 0.1, 0.01, 0.01, 0.001],
                                   rtol=1e-6)

    def test_cosine(self):
        s = opt.lr.CosineAnnealingDecay(1.0, T_max=10)
        assert abs(s() - 1.0) < 1e-9
        for _ in range(10):
            s.step()
        assert abs(s() - 0.0) < 1e-9

    def test_linear_warmup_then_constant(self):
        s = opt.lr.LinearWarmup(learning_rate=0.5, warmup_steps=5,
                                start_lr=0.0, end_lr=0.5)
        got = []
        for _ in range(7):
            got.append(s())
            s.step()
        np.testing.assert_allclose(got[:5], [0.0, 0.1, 0.2, 0.3, 0.4],
                                   rtol=1e-6)
        np.testing.assert_allclose(got[5:], [0.5, 0.5], rtol=1e-6)

    def test_warmup_wrapping_scheduler(self):
        inner = opt.lr.StepDecay(0.5, step_size=1, gamma=0.5)
        s = opt.lr.LinearWarmup(inner, warmup_steps=2, start_lr=0.0,
                                end_lr=0.5)
        got = []
        for _ in range(5):
            got.append(s())
            s.step()
        np.testing.assert_allclose(got[:2], [0.0, 0.25], rtol=1e-6)
        np.testing.assert_allclose(got[2:], [0.5, 0.25, 0.125], rtol=1e-6)

    def test_noam(self):
        s = opt.lr.NoamDecay(d_model=512, warmup_steps=4000)
        s.step()  # step 1
        expect = (512 ** -0.5) * min(1 ** -0.5, 1 * 4000 ** -1.5)
        assert abs(s() - expect) < 1e-12

    def test_reduce_on_plateau(self):
        s = opt.lr.ReduceOnPlateau(0.1, patience=1, factor=0.5)
        s.step(1.0)
        s.step(1.0)  # bad epoch 1
        s.step(1.0)  # bad epoch 2 > patience → reduce
        assert abs(s() - 0.05) < 1e-9

    def test_scheduler_drives_optimizer(self):
        p, g = _param_with_grad()
        sched = opt.lr.StepDecay(0.1, step_size=1, gamma=0.1)
        o = opt.SGD(learning_rate=sched, parameters=[p])
        assert abs(o.get_lr() - 0.1) < 1e-9
        sched.step()
        assert abs(o.get_lr() - 0.01) < 1e-9

    def test_scheduler_state_roundtrip(self):
        s = opt.lr.StepDecay(0.1, step_size=2, gamma=0.5)
        for _ in range(3):
            s.step()
        state = s.state_dict()
        s2 = opt.lr.StepDecay(0.1, step_size=2, gamma=0.5)
        s2.set_state_dict(state)
        assert s2.last_epoch == s.last_epoch
        assert abs(s2() - s()) < 1e-12


class TestOptimizerAPI:
    def test_param_groups_lr_scale(self):
        p1, g = _param_with_grad(seed=1)
        p2, _ = _param_with_grad(seed=2)
        ref1, ref2 = p1.numpy().copy(), p2.numpy().copy()
        o = opt.SGD(learning_rate=0.1, parameters=[
            {"params": [p1]},
            {"params": [p2], "learning_rate": 0.5},
        ])
        p1.grad = pt.to_tensor(g)
        p2.grad = pt.to_tensor(g)
        o.step()
        np.testing.assert_allclose(p1.numpy(), ref1 - 0.1 * g, rtol=1e-6)
        np.testing.assert_allclose(p2.numpy(), ref2 - 0.05 * g, rtol=1e-6)

    def test_clear_grad(self):
        p, g = _param_with_grad()
        o = opt.SGD(learning_rate=0.1, parameters=[p])
        assert p.grad is not None
        o.clear_grad()  # paddle-parity default keeps a zero tensor
        assert p.grad is not None
        assert np.all(p.grad.numpy() == 0)
        o.clear_grad(set_to_zero=False)
        assert p.grad is None

    def test_state_dict_roundtrip(self):
        p, g = _param_with_grad()
        o = opt.Adam(learning_rate=0.01, parameters=[p])
        _steps(o, p, g, n=2)
        sd = o.state_dict()

        p2 = pt.Parameter(p.numpy())
        o2 = opt.Adam(learning_rate=0.01, parameters=[p2])
        o2.set_state_dict(sd)
        # one more step on each must coincide
        p.grad = pt.to_tensor(g)
        p2.grad = pt.to_tensor(g)
        o.step()
        o2.step()
        np.testing.assert_allclose(p.numpy(), p2.numpy(), rtol=1e-6)

    def test_set_lr(self):
        p, _ = _param_with_grad()
        o = opt.SGD(learning_rate=0.1, parameters=[p])
        o.set_lr(0.5)
        assert o.get_lr() == 0.5

    def test_minimize(self):
        w = pt.Parameter(np.array([2.0], dtype=np.float32))
        x = pt.to_tensor(np.array([3.0], dtype=np.float32))
        o = opt.SGD(learning_rate=0.1, parameters=[w])
        loss = (w * x).sum()
        o.minimize(loss)
        np.testing.assert_allclose(w.numpy(), [2.0 - 0.1 * 3.0], rtol=1e-6)

    def test_multi_precision_master_weights(self):
        rng = np.random.RandomState(0)
        w = rng.randn(8, 8).astype(np.float32)
        p = pt.Parameter(w.astype(np.float32))
        p._data = p._data.astype("bfloat16")
        o = opt.AdamW(learning_rate=1e-3, parameters=[p],
                      multi_precision=True)
        g = rng.randn(8, 8).astype(np.float32)
        for _ in range(3):
            p.grad = pt.to_tensor(g.astype(np.float32))
            o.step()
        st = o._state[id(p)]
        assert "master_weight" in st
        assert str(st["master_weight"].dtype) == "float32"
        assert str(p.data.dtype) == "bfloat16"

    def test_mlp_converges_with_adamw(self):
        # End-to-end: the VERDICT's "done" bar — a model trains through the
        # public optimizer API.
        rng = np.random.RandomState(0)
        X = rng.randn(64, 8).astype(np.float32)
        true_w = rng.randn(8, 1).astype(np.float32)
        y = X @ true_w + 0.01 * rng.randn(64, 1).astype(np.float32)

        w1 = pt.Parameter(0.1 * rng.randn(8, 16).astype(np.float32))
        b1 = pt.Parameter(np.zeros(16, dtype=np.float32))
        w2 = pt.Parameter(0.1 * rng.randn(16, 1).astype(np.float32))
        b2 = pt.Parameter(np.zeros(1, dtype=np.float32))
        params = [w1, b1, w2, b2]
        o = opt.AdamW(learning_rate=0.01, parameters=params,
                      grad_clip=ClipGradByGlobalNorm(1.0))

        xt, yt = pt.to_tensor(X), pt.to_tensor(y)
        import paddle_tpu.nn.functional as F

        def loss_fn():
            h = F.relu(pt.matmul(xt, w1) + b1)
            pred = pt.matmul(h, w2) + b2
            return ((pred - yt) * (pred - yt)).mean()

        first = float(loss_fn().numpy())
        for _ in range(60):
            loss = loss_fn()
            loss.backward()
            o.step()
            o.clear_grad()
        last = float(loss_fn().numpy())
        assert last < first * 0.1, (first, last)
