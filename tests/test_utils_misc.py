"""paddle.utils toolbox + Orthogonal/Dirac initializers."""
import warnings

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.nn import initializer as I
from paddle_tpu.utils import unique_name


def test_orthogonal_initializer_orthonormal():
    pt.seed(0)
    w = np.asarray(I.Orthogonal()( [16, 8]))
    np.testing.assert_allclose(w.T @ w, np.eye(8), atol=1e-4)
    w2 = np.asarray(I.Orthogonal(gain=2.0)([8, 16]))
    np.testing.assert_allclose(w2 @ w2.T, 4 * np.eye(8), atol=1e-3)
    with pytest.raises(ValueError):
        I.Orthogonal()([8])


def test_dirac_initializer_identity_conv():
    w = np.asarray(I.Dirac()([4, 4, 3, 3]))
    # conv with this kernel is identity on 4 channels
    assert w.shape == (4, 4, 3, 3)
    for i in range(4):
        assert w[i, i, 1, 1] == 1.0
    assert w.sum() == 4.0
    # groups
    wg = np.asarray(I.Dirac(groups=2)([4, 2, 3]))
    assert wg[0, 0, 1] == 1.0 and wg[2, 0, 1] == 1.0
    assert wg.sum() == 4.0


def test_unique_name_generate_and_guard():
    a = unique_name.generate("fc")
    b = unique_name.generate("fc")
    assert a != b and a.startswith("fc_")
    with unique_name.guard():
        c = unique_name.generate("fc")
        assert c == "fc_0"  # fresh scope
    d = unique_name.generate("fc")
    assert d != c or d.startswith("fc_")  # outer counter restored


def test_deprecated_decorator():
    @pt.utils.deprecated(update_to="paddle.new_api", since="2.5")
    def old_api():
        return 42

    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        assert old_api() == 42
    assert any("deprecated" in str(w.message) for w in rec)

    @pt.utils.deprecated(level=2)
    def dead_api():
        return 1

    with pytest.raises(RuntimeError):
        dead_api()


def test_try_import():
    assert pt.utils.try_import("json") is not None
    with pytest.raises(ImportError, match="not installed"):
        pt.utils.try_import("definitely_not_a_module_xyz")


def test_dlpack_roundtrip_with_torch():
    import torch
    t = torch.arange(6, dtype=torch.float32).reshape(2, 3)
    x = pt.utils.from_dlpack(t)
    np.testing.assert_allclose(np.asarray(x.data),
                               t.numpy(), rtol=1e-6)
    cap = pt.utils.to_dlpack(x)
    back = torch.from_dlpack(cap)
    np.testing.assert_allclose(back.numpy(), t.numpy(), rtol=1e-6)


def test_download_raises_and_run_check(capsys):
    with pytest.raises(NotImplementedError):
        pt.utils.get_weights_path_from_url("http://example.com/w.pdparams")
    assert pt.utils.run_check()
    assert "works on" in capsys.readouterr().out


def test_local_fs(tmp_path):
    import os
    from paddle_tpu.distributed.fleet.utils import LocalFS, HDFSClient
    fs = LocalFS()
    d = os.path.join(tmp_path, "ckpt")
    fs.mkdirs(d)
    assert fs.is_dir(d)
    f = os.path.join(d, "model.pdparams")
    fs.touch(f)
    assert fs.is_file(f) and fs.is_exist(f)
    dirs, files = fs.ls_dir(d)
    assert files == ["model.pdparams"]
    f2 = os.path.join(d, "renamed.pdparams")
    fs.rename(f, f2)
    assert fs.is_file(f2) and not fs.is_exist(f)
    fs.delete(d)
    assert not fs.is_exist(d)

    hdfs = HDFSClient()
    with pytest.raises(RuntimeError, match="hadoop"):
        hdfs.ls_dir("/remote/path")


def test_clip_grad_value_exported_and_works():
    import paddle_tpu.nn as nn
    from paddle_tpu.nn.utils import clip_grad_value_
    m = nn.Linear(3, 3)
    x = pt.to_tensor(np.full((2, 3), 10.0, np.float32))
    pt.ops.sum(m(x)).backward()
    clip_grad_value_(m.parameters(), 0.5)
    for _, p in m.named_parameters():
        g = np.asarray(p.grad.data)
        assert np.all(np.abs(g) <= 0.5 + 1e-7)
