"""paddle.text (viterbi_decode) and paddle.audio (features/functional/
backends) — numpy/scipy oracles, kernel-parity checks."""
import math
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import audio, text


# ---------------------------------------------------------------- viterbi
def _brute_force_viterbi(emission, trans, length, bos_eos):
    """Exhaustive search oracle over all tag paths of one sample."""
    n = emission.shape[1]
    best_score, best_path = -np.inf, None
    import itertools
    for path in itertools.product(range(n), repeat=length):
        s = emission[0, path[0]]
        if bos_eos:
            s += trans[n - 1, path[0]]
        for t in range(1, length):
            s += trans[path[t - 1], path[t]] + emission[t, path[t]]
        if bos_eos:
            # kernel convention (viterbi_decode_kernel.cc:273-280): the
            # stop contribution is ROW n-2 of the transitions, added to
            # alpha over the current tag
            s += trans[n - 2, path[length - 1]]
        if s > best_score:
            best_score, best_path = s, path
    return best_score, list(best_path)


@pytest.mark.parametrize("bos_eos", [False, True])
def test_viterbi_matches_brute_force(bos_eos):
    rng = np.random.RandomState(0)
    B, T, N = 3, 5, 4
    emission = rng.randn(B, T, N).astype(np.float32)
    trans = rng.randn(N, N).astype(np.float32)
    lengths = np.array([5, 3, 1], np.int64)
    scores, paths = text.viterbi_decode(
        pt.to_tensor(emission), pt.to_tensor(trans), pt.to_tensor(lengths),
        include_bos_eos_tag=bos_eos)
    scores = np.asarray(scores.data)
    paths = np.asarray(paths.data)
    assert paths.shape == (B, 5)  # batch max length
    for b in range(B):
        L = int(lengths[b])
        want_s, want_p = _brute_force_viterbi(emission[b], trans, L, bos_eos)
        np.testing.assert_allclose(scores[b], want_s, rtol=1e-5,
                                   err_msg=f"sample {b}")
        assert list(paths[b][:L]) == want_p, (b, paths[b], want_p)
        assert np.all(paths[b][L:] == 0)


def test_viterbi_decoder_layer():
    rng = np.random.RandomState(1)
    trans = rng.randn(3, 3).astype(np.float32)
    dec = text.ViterbiDecoder(pt.to_tensor(trans), include_bos_eos_tag=False)
    em = pt.to_tensor(rng.randn(2, 4, 3).astype(np.float32))
    lens = pt.to_tensor(np.array([4, 4], np.int64))
    scores, paths = dec(em, lens)
    assert list(paths.shape) == [2, 4]


# ---------------------------------------------------------------- audio fn
def test_mel_scale_roundtrip():
    for htk in (False, True):
        for f in (60.0, 440.0, 4000.0):
            m = audio.functional.hz_to_mel(f, htk)
            back = audio.functional.mel_to_hz(m, htk)
            assert abs(back - f) < 1e-6 * max(f, 1), (htk, f, back)


def test_fbank_matrix_vs_librosa_math():
    fb = audio.functional.compute_fbank_matrix(sr=16000, n_fft=512,
                                               n_mels=40).numpy()
    assert fb.shape == (40, 257)
    assert np.all(fb >= 0)
    # every interior filter must have some support
    assert (fb.sum(axis=1) > 0).sum() >= 38


def test_windows_match_scipy():
    from scipy.signal import get_window as sp_get_window
    for name in ("hann", "hamming", "blackman", "cosine"):
        for fftbins in (True, False):
            got = audio.functional.get_window(name, 32, fftbins).numpy()
            want = sp_get_window(name, 32, fftbins)
            np.testing.assert_allclose(got, want, atol=1e-6,
                                       err_msg=f"{name} fftbins={fftbins}")


def test_create_dct_orthonormal():
    d = audio.functional.create_dct(13, 40).numpy()
    assert d.shape == (40, 13)
    np.testing.assert_allclose(d.T @ d, np.eye(13), atol=1e-6)


def test_power_to_db_oracle():
    s = pt.to_tensor(np.array([1.0, 0.1, 1e-12], np.float32))
    db = audio.functional.power_to_db(s, top_db=80.0).numpy()
    np.testing.assert_allclose(db[0], 0.0, atol=1e-5)
    np.testing.assert_allclose(db[1], -10.0, atol=1e-4)
    assert db[2] == pytest.approx(-80.0, abs=1e-4)  # floored by top_db


# ------------------------------------------------------------ audio layers
def test_spectrogram_parseval_and_peak():
    """A pure sine's spectrogram must peak at its own frequency bin."""
    sr, n_fft = 8000, 256
    t = np.arange(sr, dtype=np.float32) / sr
    freq = 1000.0
    wav = np.sin(2 * math.pi * freq * t)[None, :2048]
    spec = audio.Spectrogram(n_fft=n_fft, hop_length=128)(
        pt.to_tensor(wav))
    s = np.asarray(spec.data)[0]      # [freq, frames]
    peak_bin = int(s.mean(axis=1).argmax())
    want_bin = int(round(freq * n_fft / sr))
    assert abs(peak_bin - want_bin) <= 1


def test_spectrogram_matches_scipy_stft():
    from scipy.signal import stft as sp_stft
    rng = np.random.RandomState(2)
    wav = rng.randn(1024).astype(np.float32)
    n_fft, hop = 128, 64
    spec = audio.Spectrogram(n_fft=n_fft, hop_length=hop, power=1.0,
                             center=True, pad_mode="reflect")(
        pt.to_tensor(wav[None]))
    got = np.asarray(spec.data)[0]
    _, _, Z = sp_stft(wav, nperseg=n_fft, noverlap=n_fft - hop,
                      window="hann", boundary="even", padded=False)
    want = np.abs(Z) * (n_fft / 2)  # scipy normalizes by window.sum()
    k = min(got.shape[1], want.shape[1])
    np.testing.assert_allclose(got[:, 1:k - 1], want[:, 1:k - 1],
                               rtol=1e-3, atol=1e-4)


def test_mfcc_pipeline_shapes_and_grad():
    mfcc = audio.MFCC(sr=8000, n_mfcc=13, n_fft=256, n_mels=32)
    wav = pt.to_tensor(np.random.RandomState(3).randn(2, 2048)
                       .astype(np.float32))
    wav.stop_gradient = False
    out = mfcc(wav)
    assert out.shape[0] == 2 and out.shape[1] == 13
    pt.ops.sum(out).backward()   # differentiable back to the waveform
    assert wav.grad is not None
    assert np.all(np.isfinite(np.asarray(wav.grad.data)))


# -------------------------------------------------------------- backends
def test_wav_roundtrip(tmp_path):
    sr = 8000
    t = np.arange(1600, dtype=np.float32) / sr
    wav = 0.5 * np.sin(2 * math.pi * 440 * t)[None, :]  # [1, T]
    path = os.path.join(tmp_path, "tone.wav")
    audio.backends.save(path, wav, sr)
    meta = audio.backends.info(path)
    assert meta.sample_rate == sr and meta.num_channels == 1
    loaded, sr2 = audio.backends.load(path)
    assert sr2 == sr
    np.testing.assert_allclose(np.asarray(loaded.data)[0], wav[0],
                               atol=1e-3)


# ------------------------------------------------------------ text datasets
def test_uci_housing_local_file(tmp_path):
    rng = np.random.RandomState(4)
    rows = np.hstack([rng.rand(50, 13), rng.rand(50, 1) * 50])
    f = os.path.join(tmp_path, "housing.data")
    np.savetxt(f, rows)
    ds = text.datasets.UCIHousing(data_file=f, mode="train")
    assert len(ds) == 40  # 80% split
    x, y = ds[0]
    assert x.shape == (13,) and y.shape == (1,)
    assert x.min() >= 0 and x.max() <= 1  # normalized


def test_datasets_require_local_file():
    with pytest.raises(FileNotFoundError):
        text.datasets.Imdb()
    with pytest.raises(FileNotFoundError):
        text.datasets.WMT14()


def test_imdb_single_pass_local_tar(tmp_path):
    """Tiny synthetic aclImdb tar: dict built + docs loaded in one scan."""
    import io
    import tarfile
    path = os.path.join(tmp_path, "aclImdb.tar.gz")
    reviews = {"aclImdb/train/pos/0_9.txt": b"great great movie",
               "aclImdb/train/pos/1_8.txt": b"great fun",
               "aclImdb/train/neg/0_2.txt": b"terrible movie"}
    with tarfile.open(path, "w:gz") as tf:
        for name, data in reviews.items():
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    ds = text.datasets.Imdb(data_file=path, mode="train", cutoff=1)
    assert len(ds) == 3
    labels = sorted(int(l) for _, l in [ds[i] for i in range(3)])
    assert labels == [0, 0, 1]
    assert "great" in ds.word_idx and "terrible" in ds.word_idx


def test_wav_save_1d_channels_last(tmp_path):
    """1-D waveform with channels_first=False must still be one channel."""
    sr = 8000
    wav = np.zeros(1600, np.float32)
    path = os.path.join(tmp_path, "flat.wav")
    audio.backends.save(path, wav, sr, channels_first=False)
    meta = audio.backends.info(path)
    assert meta.num_channels == 1 and meta.num_frames == 1600
