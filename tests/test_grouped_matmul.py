"""Grouped-matmul Pallas kernel (the cutlass moe_kernel.cu analog,
ops/pallas/grouped_matmul.py): forward + custom_vjp parity vs per-group
numpy/jax oracles, in interpret mode on CPU (the kernels compile for TPU
on chip). Includes empty groups, non-divisible row counts, and the
bm-aligned mask-free fast path."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.grouped_matmul import gmm, gmm_aligned, tgmm

E, M, H, BM = 4, 64, 32, 8


@pytest.mark.parametrize("sizes", [[5, 0, 11, 3], [8, 8, 8, 8],
                                   [0, 0, 30, 2], [1, 1, 1, 1]])
def test_gmm_forward_and_grads_match_oracle(sizes):
    rng = np.random.RandomState(sum(sizes))
    R = 40
    gs = np.array(sizes, np.int32)
    lhs = rng.randn(R, M).astype(np.float32)
    rhs = rng.randn(E, M, H).astype(np.float32)
    out = gmm(jnp.asarray(lhs), jnp.asarray(rhs), jnp.asarray(gs), bm=BM)
    want = np.zeros((R, H), np.float32)
    off = 0
    for e in range(E):
        want[off:off + gs[e]] = lhs[off:off + gs[e]] @ rhs[e]
        off += gs[e]
    np.testing.assert_allclose(np.asarray(out), want, atol=1e-4)

    def loss(l, r):
        return (gmm(l, r, jnp.asarray(gs), bm=BM) ** 2).sum()

    gl, gr = jax.grad(loss, argnums=(0, 1))(jnp.asarray(lhs),
                                            jnp.asarray(rhs))

    def loss_ref(l, r):
        outs, o = [], 0
        for e in range(E):
            n = int(gs[e])
            outs.append(l[o:o + n] @ r[e])
            o += n
        outs.append(jnp.zeros((R - o, H)))
        return (jnp.concatenate(outs) ** 2).sum()

    gl2, gr2 = jax.grad(loss_ref, argnums=(0, 1))(jnp.asarray(lhs),
                                                  jnp.asarray(rhs))
    np.testing.assert_allclose(np.asarray(gl), np.asarray(gl2), atol=1e-3)
    np.testing.assert_allclose(np.asarray(gr), np.asarray(gr2), atol=1e-3)


def test_tgmm_matches_oracle():
    rng = np.random.RandomState(7)
    gs = np.array([5, 0, 11, 3], np.int32)
    lhs = rng.randn(40, M).astype(np.float32)
    g = rng.randn(40, H).astype(np.float32)
    out = tgmm(jnp.asarray(lhs), jnp.asarray(g), jnp.asarray(gs), E, bm=BM)
    want = np.zeros((E, M, H), np.float32)
    off = 0
    for e in range(E):
        want[e] = lhs[off:off + gs[e]].T @ g[off:off + gs[e]]
        off += gs[e]
    np.testing.assert_allclose(np.asarray(out), want, atol=1e-3)


@pytest.mark.parametrize("sizes", [[16, 0, 24, 8], [8, 8, 8, 8],
                                   [0, 0, 40, 0]])
def test_gmm_aligned_forward_and_grads(sizes):
    """bm-aligned groups: the mask-free fast path; pad rows must be zero
    and produce zeros, empty experts get zero d_rhs (not garbage)."""
    rng = np.random.RandomState(sum(sizes) + 1)
    gs = np.array(sizes, np.int32)
    R = 48
    lhs = rng.randn(R, M).astype(np.float32)
    lhs[gs.sum():] = 0
    rhs = rng.randn(E, M, H).astype(np.float32)
    out = gmm_aligned(jnp.asarray(lhs), jnp.asarray(rhs), jnp.asarray(gs),
                      bm=BM)
    off = 0
    want = np.zeros((R, H), np.float32)
    for e in range(E):
        want[off:off + gs[e]] = lhs[off:off + gs[e]] @ rhs[e]
        off += gs[e]
    np.testing.assert_allclose(np.asarray(out)[:off], want[:off],
                               atol=1e-4)

    def loss(l, r):
        return (gmm_aligned(l, r, jnp.asarray(gs), bm=BM)[:off] ** 2).sum()

    gl, gr = jax.grad(loss, argnums=(0, 1))(jnp.asarray(lhs),
                                            jnp.asarray(rhs))

    def loss_ref(l, r):
        outs, o = [], 0
        for e in range(E):
            n = int(gs[e])
            outs.append(l[o:o + n] @ r[e])
            o += n
        return (jnp.concatenate(outs) ** 2).sum()

    gl2, gr2 = jax.grad(loss_ref, argnums=(0, 1))(jnp.asarray(lhs),
                                                  jnp.asarray(rhs))
    np.testing.assert_allclose(np.asarray(gl)[:off], np.asarray(gl2)[:off],
                               atol=1e-3)
    np.testing.assert_allclose(np.asarray(gr), np.asarray(gr2), atol=1e-3)
    assert np.isfinite(np.asarray(gr)).all()


def test_gmm_rejects_undivisible_rows():
    with pytest.raises(ValueError, match="divide"):
        gmm(jnp.zeros((10, M)), jnp.zeros((E, M, H)),
            jnp.asarray(np.array([10, 0, 0, 0], np.int32)), bm=BM)
