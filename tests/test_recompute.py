"""recompute (activation checkpointing) — gradient parity with the
non-recomputed path (reference contract:
``python/paddle/distributed/fleet/utils`` recompute)."""
import numpy as np

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.distributed.fleet.utils import recompute, recompute_sequential


def _mlp(seed=0):
    pt.seed(seed)
    return nn.Sequential(
        nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 8), nn.GELU())


def _grads(model):
    return {n: np.asarray(p.grad.data) for n, p in model.named_parameters()}


def test_recompute_layer_grad_parity():
    x = np.random.RandomState(0).randn(4, 8).astype(np.float32)

    ref = _mlp()
    out = ref(pt.to_tensor(x))
    pt.ops.sum(out).backward()
    want = _grads(ref)

    rc = _mlp()
    out2 = recompute(rc, pt.to_tensor(x))
    np.testing.assert_allclose(np.asarray(out2.data), np.asarray(out.data),
                               rtol=1e-5)
    pt.ops.sum(out2).backward()
    got = _grads(rc)

    assert set(got) == set(want)
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-5, atol=1e-6,
                                   err_msg=k)


def test_recompute_bound_method():
    model = _mlp(seed=1)
    x = pt.to_tensor(np.random.RandomState(1).randn(2, 8).astype(np.float32))
    out = recompute(model.forward, x)
    pt.ops.sum(out).backward()
    for _, p in model.named_parameters():
        assert p.grad is not None


def test_recompute_plain_function_input_grad():
    x = pt.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
    x.stop_gradient = False
    y = recompute(lambda t: pt.ops.sum(pt.ops.multiply(t, t)), x)
    y.backward()
    np.testing.assert_allclose(np.asarray(x.grad.data),
                               2 * np.asarray(x.data), rtol=1e-6)


def test_recompute_sequential_segments():
    x = np.random.RandomState(2).randn(3, 8).astype(np.float32)
    ref = _mlp(seed=2)
    out = ref(pt.to_tensor(x))
    pt.ops.sum(out).backward()
    want = _grads(ref)

    rc = _mlp(seed=2)
    out2 = recompute_sequential({"segments": 2}, list(rc), pt.to_tensor(x))
    np.testing.assert_allclose(np.asarray(out2.data), np.asarray(out.data),
                               rtol=1e-5)
    pt.ops.sum(out2).backward()
    got = _grads(rc)
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-5, atol=1e-6,
                                   err_msg=k)


def test_recompute_with_dropout_is_consistent():
    """The rematerialized forward must reuse the same dropout mask (the
    'preserve_rng_state' contract) — grads of an identity-through-dropout
    chain must match the saved-activation path exactly."""
    pt.seed(7)
    model = nn.Sequential(nn.Linear(8, 8), nn.Dropout(0.5), nn.Linear(8, 4))
    x = pt.to_tensor(np.random.RandomState(3).randn(4, 8).astype(np.float32))
    out = recompute(model, x)
    pt.ops.sum(out).backward()
    # finite grads on every parameter is the smoke contract; exact mask
    # parity is inherent to XLA remat (same traced RNG values)
    for _, p in model.named_parameters():
        assert np.all(np.isfinite(np.asarray(p.grad.data)))


def test_recompute_closure_over_layer_gets_grads():
    """The ``recompute(lambda x: self.mlp(x), h)`` idiom: closed-over
    Layer params must receive gradients."""
    model = _mlp(seed=9)
    x = pt.to_tensor(np.random.RandomState(9).randn(2, 8).astype(np.float32))
    out = recompute(lambda t: model(t), x)
    pt.ops.sum(out).backward()
    for n, p in model.named_parameters():
        assert p.grad is not None, n
        assert float(np.abs(np.asarray(p.grad.data)).sum()) > 0, n


def test_recompute_partial_and_container_closures():
    """functools.partial and container-held layers must also get grads."""
    import functools
    model = _mlp(seed=11)
    x = pt.to_tensor(np.random.RandomState(11).randn(2, 8)
                     .astype(np.float32))

    def run(layer, t):
        return layer(t)

    out = recompute(functools.partial(run, model), x)
    pt.ops.sum(out).backward()
    for n, p in model.named_parameters():
        assert p.grad is not None, n

    model2 = _mlp(seed=12)
    layers = [model2]
    out2 = recompute(lambda t: layers[0](t), x)
    pt.ops.sum(out2).backward()
    for n, p in model2.named_parameters():
        assert p.grad is not None, n
