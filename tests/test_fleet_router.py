"""Serving fleet: cache-aware multi-replica router (ISSUE 17).

Pins the four fleet contracts end to end on CPU:

- **Placement** — sketch-affinity routing lands shared-prefix traffic
  on the replica already holding the blocks; least-loaded is the
  fallback and the ``PADDLE_TPU_ROUTER_*`` knobs gate both.
- **Chaos/failover** — a replica stub-killed mid-stream fails over to
  a survivor with the greedy stream token-identical to the eager
  oracle, no streamed token duplicated, and the re-admission's
  tail-only recompute pinned via the request ledger's
  ``cached_tokens`` / ``prefilled_tokens`` fields.
- **Disaggregation** — long prompts prefill on a ``prefill``-role
  replica, the KV blocks host-stage into a ``decode`` replica, and the
  decoded stream still matches eager greedy exactly.
- **Front-end** — ``RouterServer``'s /generate traceparent echo,
  /fleetz, /statusz fleet section, and the fleet-saturated 503 shed
  path (Retry-After + traceparent echo +
  ``serving_rejections_total{reason="fleet_saturated"}``), plus one
  ``trace merge --requests`` chain spanning router, prefill replica,
  and decode replica.
"""
import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability import requests as obs_requests
from paddle_tpu.observability import trace
from paddle_tpu.serving import (FleetRouter, Replica, RouterServer,
                                ServingEngine)
from paddle_tpu.serving.engine import serving_metrics
from paddle_tpu.serving.fleet import build_fleet, router_metrics

ENG_KW = dict(max_batch=4, max_blocks=32, block_size=4, prefill_chunk=8)


def _tiny(seed=0):
    pt.seed(seed)
    m = LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, tie_word_embeddings=True))
    m.eval()
    return m


def _eager(model, prompt, n, eos=None):
    out = model.generate(pt.to_tensor(np.asarray(prompt)[None, :]),
                         max_new_tokens=n, temperature=0.0,
                         eos_token_id=eos).numpy()[0]
    return [int(t) for t in out[len(prompt):]]


def _mk_replica(name, role="mixed", seed=0):
    return Replica(ServingEngine(_tiny(seed), **ENG_KW), name, role=role)


@pytest.fixture(scope="module")
def oracle():
    return _tiny(0)


@pytest.fixture(scope="module")
def fleet2(oracle):
    """Two mixed replicas behind one router (shared by the
    non-destructive placement tests)."""
    reps = [_mk_replica(f"r{i}") for i in range(2)]
    router = FleetRouter(reps, prefill_threshold=64)
    router.start()
    yield router, reps
    router.shutdown(drain=True)
    for r in reps:
        if r.alive:
            r.engine.cache.allocator.assert_no_leaks()


class TestPlacement:
    def test_basic_parity_and_stats(self, fleet2, oracle):
        router, reps = fleet2
        rng = np.random.RandomState(0)
        prompt = [int(t) for t in rng.randint(1, 128, 9)]
        res = router.submit(prompt, max_new_tokens=6).result(timeout=120)
        assert res["token_ids"] == _eager(oracle, prompt, 6)
        assert res["failovers"] == 0
        s = router.stats()
        assert s["replicas"] == 2 and s["replicas_live"] == 2
        assert s["routing"]["least_loaded"] + s["routing"]["affinity"] >= 1
        fz = router.fleetz()
        assert [p["name"] for p in fz["per_replica"]] == ["r0", "r1"]

    def test_affinity_routes_to_warmed_replica(self, fleet2, oracle):
        router, reps = fleet2
        rng = np.random.RandomState(1)
        shared = [int(t) for t in rng.randint(1, 128, 12)]
        # warm r1's prefix cache out-of-band, then route a request that
        # extends the same prefix: the sketch match must pin it to r1
        reps[1].engine.submit(shared, max_new_tokens=2).result(timeout=120)
        reps[1].engine.drain(timeout=120)
        before = router.decisions["affinity"]
        h = router.submit(shared + [5, 6], max_new_tokens=4)
        res = h.result(timeout=120)
        assert res["token_ids"] == _eager(oracle, shared + [5, 6], 4)
        assert router.decisions["affinity"] == before + 1
        assert h._attempt_replica.name == "r1"

    def test_affinity_off_falls_back_least_loaded(self):
        reps = [_mk_replica("a0"), _mk_replica("a1")]
        router = FleetRouter(reps, affinity=False, disagg=False)
        router.start()
        try:
            rng = np.random.RandomState(2)
            prompt = [int(t) for t in rng.randint(1, 128, 8)]
            router.submit(prompt, max_new_tokens=3).result(timeout=120)
            assert router.decisions["affinity"] == 0
            assert router.decisions["least_loaded"] == 1
        finally:
            router.shutdown(drain=True)

    def test_env_knobs_gate_policies(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_ROUTER_AFFINITY", "0")
        monkeypatch.setenv("PADDLE_TPU_ROUTER_DISAGG", "0")
        monkeypatch.setenv("PADDLE_TPU_ROUTER_PREFILL_THRESHOLD", "32")
        router = FleetRouter([_mk_replica("k0")])
        assert router.affinity is False
        assert router.disagg is False
        assert router.prefill_threshold == 32

    def test_build_fleet_env_replica_count(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FLEET_REPLICAS", "3")
        reps = build_fleet(_tiny, roles=["prefill"], **ENG_KW)
        assert [r.role for r in reps] == ["prefill", "mixed", "mixed"]
        assert len({r.name for r in reps}) == 3
        for r in reps:
            r.kill()


class TestChaosFailover:
    def test_mid_stream_kill_failover_greedy_identical(self, oracle):
        """Stub-kill the replica serving a stream after >=3 tokens: the
        survivor must complete it token-identically (no duplicates),
        recomputing only the tail of the re-admitted prompt."""
        led = obs_requests.maybe_arm()
        assert led is not None
        old_rate = led.sample_rate
        led.sample_rate = 1.0  # keep every record: the pin reads the ring
        reps = [_mk_replica("c0"), _mk_replica("c1")]
        router = FleetRouter(reps, prefill_threshold=64)
        router.start()
        try:
            rng = np.random.RandomState(7)
            # compile both replicas' steps up front so the kill window
            # below is not racing a cold jit compile
            for r in reps:
                r.engine.submit([int(t) for t in rng.randint(1, 128, 5)],
                                max_new_tokens=2).result(timeout=120)
                r.engine.drain(timeout=120)
            prompt = [int(t) for t in rng.randint(1, 128, 7)]
            got, seen3, killed = [], threading.Event(), threading.Event()

            def on_tok(_h, t):
                got.append(t)
                if len(got) >= 3:
                    seen3.set()
                    if not killed.is_set():
                        # stall the victim's decode loop (the callback
                        # runs inside it) so the stream cannot finish
                        # before the plug is pulled
                        killed.wait(0.15)

            tid = "f1ee7000" * 4
            h = router.submit(prompt, max_new_tokens=16, on_token=on_tok,
                              trace_id=tid)
            assert seen3.wait(60)
            victim = h._attempt_replica
            survivor = reps[1] if victim is reps[0] else reps[0]
            # warm the survivor with the original prompt so the
            # re-admission is a prefix-cache hit, then pull the plug
            survivor.engine.submit(
                prompt, max_new_tokens=2).result(timeout=120)
            survivor.engine.drain(timeout=120)
            victim.kill()
            killed.set()
            res = h.result(timeout=120)
            exp = _eager(oracle, prompt, 16)
            assert res["token_ids"] == exp
            assert got == exp  # streamed exactly once, in order
            assert res["failovers"] == 1
            assert router.decisions["failover"] == 1
            assert router.stats()["replicas_dead"] == 1
            # tail-only recompute: the survivor attempt's ledger record
            # reused the prompt's full blocks and cold-prefilled only
            # the tail of (prompt + already-streamed tokens)
            recs = [d for d in led.exemplars()
                    if d["trace_id"] == tid and d["error"] is None]
            assert recs, "survivor attempt record not kept"
            rec = recs[-1]
            assert rec["cached_tokens"] >= ENG_KW["block_size"]
            assert rec["prefilled_tokens"] < rec["prompt_len"]
            assert rec["cached_tokens"] + rec["prefilled_tokens"] \
                == rec["prompt_len"]
        finally:
            led.sample_rate = old_rate
            router.shutdown(drain=True)


class TestDisaggregation:
    def test_prefill_decode_handoff_parity(self, oracle):
        pre = _mk_replica("pre0", role="prefill")
        dec = _mk_replica("dec0", role="decode")
        router = FleetRouter([pre, dec], prefill_threshold=12)
        router.start()
        try:
            m = router_metrics()
            blocks_before = m["kv_handoff_blocks"].value()
            rng = np.random.RandomState(3)
            prompt = [int(t) for t in rng.randint(1, 128, 17)]
            res = router.submit(prompt, max_new_tokens=6).result(
                timeout=120)
            assert res["token_ids"] == _eager(oracle, prompt, 6)
            assert router.decisions["disagg_prefill"] == 1
            # the decode replica admitted the imported blocks as a
            # prefix-cache hit: 17 tokens / block 4 -> 4 staged blocks
            ds = dec.engine.stats()["prefix_cache"]
            assert ds["hits"] >= 1 and ds["entries"] >= 4
            assert m["kv_handoff_blocks"].value() - blocks_before >= 4
            # short prompts skip the prefill hop entirely
            router.submit([int(t) for t in rng.randint(1, 128, 6)],
                          max_new_tokens=3).result(timeout=120)
            assert router.decisions["disagg_prefill"] == 1
        finally:
            router.shutdown(drain=True)
        pre.engine.cache.allocator.assert_no_leaks()
        dec.engine.cache.allocator.assert_no_leaks()


class TestRouterServer:
    def test_endpoints_shed_and_trace_chain(self, oracle, tmp_path):
        trace.enable(str(tmp_path))
        pre = _mk_replica("pre0", role="prefill")
        dec = _mk_replica("dec0", role="decode")
        mix = _mk_replica("mix0", role="mixed")
        router = FleetRouter([pre, dec, mix], prefill_threshold=12)
        srv = RouterServer(router, max_queue_depth=4).start()
        tid = "ab" * 16
        try:
            rng = np.random.RandomState(5)
            prompt = [int(t) for t in rng.randint(1, 128, 17)]
            body = json.dumps({"prompt_ids": prompt,
                               "max_new_tokens": 5}).encode()
            req = urllib.request.Request(
                f"{srv.url}/generate", data=body,
                headers={"Content-Type": "application/json",
                         "traceparent":
                         f"00-{tid}-b7ad6b7169203331-01"})
            r = urllib.request.urlopen(req, timeout=120)
            res = json.loads(r.read())
            assert res["token_ids"] == _eager(oracle, prompt, 5)
            assert res["trace_id"] == tid
            assert tid in r.headers.get("traceparent", "")

            fz = json.loads(urllib.request.urlopen(
                f"{srv.url}/fleetz", timeout=30).read())
            assert fz["replicas"] == 3 and len(fz["per_replica"]) == 3
            assert fz["routing"]["disagg_prefill"] >= 1

            sz = json.loads(urllib.request.urlopen(
                f"{srv.url}/statusz?format=json", timeout=30).read())
            assert "fleet" in sz
            html = urllib.request.urlopen(
                f"{srv.url}/statusz", timeout=30).read().lower()
            assert b"<table" in html or b"<html" in html

            # fleet-saturated shed: depth 0 saturates every replica
            srv.max_queue_depth = 0
            rej = serving_metrics()["rejections"]
            before = rej.value(reason="fleet_saturated")
            req503 = urllib.request.Request(
                f"{srv.url}/generate", data=body,
                headers={"Content-Type": "application/json",
                         "traceparent":
                         f"00-{'cd' * 16}-b7ad6b7169203331-01"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req503, timeout=30)
            e = ei.value
            assert e.code == 503
            assert e.headers.get("Retry-After")
            assert "cd" * 16 in e.headers.get("traceparent", "")
            assert "fleet" in json.loads(e.read())["error"]
            assert rej.value(reason="fleet_saturated") == before + 1
            srv.max_queue_depth = 4
        finally:
            srv.close(drain=True)
            trace.disable()

        # one merge --requests chain spans router + prefill replica +
        # decode replica: router_route/router_handoff plus the
        # replicas' own serving spans, all on the request's trace id
        summary = trace.merge(str(tmp_path), requests=True)
        rollup = summary.get("requests_rollup") or summary.get("requests")
        chain = rollup["requests"].get(tid)
        assert chain is not None and chain["spans"] >= 4
        import os
        with open(os.path.join(str(tmp_path), "merged_trace.json")) as f:
            ev = json.load(f)
        names = {e.get("name") for e in ev.get("traceEvents", ev)
                 if isinstance(e, dict)
                 and (e.get("args") or {}).get("trace") == tid}
        assert "router_route" in names and "router_handoff" in names
