"""Observability layer: metrics registry + exposition sinks, step
telemetry through Model.fit, collective-comm tracing, flight recorder
postmortems, bench.py metric emission (docs/OBSERVABILITY.md)."""
import json
import os
import sys
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.profiler as profiler
from paddle_tpu.observability import (
    MetricsRegistry, StepTimer, comm_totals, flight_recorder, get_registry,
    payload_bytes,
)
from paddle_tpu.observability.metrics import MetricsExporter


@pytest.fixture
def recorder_off():
    """Ensure the flight recorder never leaks across tests."""
    flight_recorder.disable()
    yield
    flight_recorder.disable()


class TestMetricsRegistry:
    def test_counter_labels(self):
        reg = MetricsRegistry()
        c = reg.counter("reqs_total", "requests")
        c.inc(2, route="/a")
        c.inc(route="/a")
        c.inc(5, route="/b")
        assert c.value(route="/a") == 3
        assert c.value(route="/b") == 5
        assert c.total() == 8

    def test_counter_monotonic(self):
        c = MetricsRegistry().counter("x")
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_gauge_set_inc(self):
        g = MetricsRegistry().gauge("temp")
        g.set(3.5, zone="hot")
        g.inc(0.5, zone="hot")
        g.dec(1.0, zone="hot")
        assert g.value(zone="hot") == pytest.approx(3.0)

    def test_histogram_buckets(self):
        h = MetricsRegistry().histogram("lat", buckets=[0.1, 1.0, 10.0])
        for v in (0.05, 0.5, 5.0, 50.0):
            h.observe(v)
        st = h.stats()
        assert st["count"] == 4
        assert st["sum"] == pytest.approx(55.55)

    def test_kind_conflict(self):
        reg = MetricsRegistry()
        reg.counter("m")
        with pytest.raises(ValueError):
            reg.gauge("m")

    def test_prometheus_text(self):
        reg = MetricsRegistry()
        reg.counter("hits_total", "hit count").inc(7, kind="a")
        reg.gauge("depth").set(2.5)
        reg.histogram("t", buckets=[1.0]).observe(0.5)
        text = reg.prometheus_text()
        assert "# TYPE hits_total counter" in text
        assert 'hits_total{kind="a"} 7.0' in text
        assert "depth 2.5" in text
        assert 't_bucket{le="1.0"} 1' in text
        assert 't_bucket{le="+Inf"} 1' in text
        assert "t_sum 0.5" in text and "t_count 1" in text

    def test_json_exposition(self):
        reg = MetricsRegistry()
        reg.counter("c").inc(3, op="x")
        reg.histogram("h", buckets=[1.0]).observe(0.5)
        doc = reg.to_json()
        assert doc["c"]["type"] == "counter"
        assert doc["c"]["samples"][0] == {"labels": {"op": "x"}, "value": 3.0}
        assert doc["h"]["samples"][0]["count"] == 1
        json.dumps(doc)  # fully serializable

    def test_reset_keeps_registrations(self):
        reg = MetricsRegistry()
        c = reg.counter("c")
        c.inc(3)
        reg.reset()
        assert c.value() == 0
        assert reg.get("c") is c

    def test_http_exporter(self):
        reg = MetricsRegistry()
        reg.gauge("scrape_me").set(42.0)
        exp = MetricsExporter(0, reg)  # ephemeral port
        try:
            base = f"http://127.0.0.1:{exp.port}"
            text = urllib.request.urlopen(f"{base}/metrics").read().decode()
            assert "scrape_me 42.0" in text
            doc = json.loads(urllib.request.urlopen(
                f"{base}/metrics.json").read().decode())
            assert doc["scrape_me"]["samples"][0]["value"] == 42.0
        finally:
            exp.stop()


class TestStepTimer:
    def test_decomposition_and_rates(self):
        reg = MetricsRegistry()
        timer = StepTimer(registry=reg, flops_per_sample=1e6, peak=1e9)
        timer.begin_step(data_time=0.25)
        stats = timer.end_step(samples=10, tokens=1000)
        assert stats["data_time_s"] == pytest.approx(0.25)
        assert stats["step_time_s"] > 0.25
        assert stats["compute_time_s"] >= 0
        assert stats["collective_time_s"] == 0.0
        assert stats["samples_per_sec"] == pytest.approx(
            10 / stats["step_time_s"])
        assert stats["tokens_per_sec"] == pytest.approx(
            1000 / stats["step_time_s"])
        assert stats["mfu"] == pytest.approx(
            10 * 1e6 / stats["step_time_s"] / 1e9)
        assert reg.counter("train_steps_total").value() == 1
        assert reg.get("train_step_seconds").stats()["count"] == 1

    def test_tokens_per_sample_hint(self):
        timer = StepTimer(registry=MetricsRegistry(), tokens_per_sample=128,
                          peak=0)
        timer.begin_step()
        stats = timer.end_step(samples=4)
        assert stats["tokens_per_sec"] == pytest.approx(
            4 * 128 / stats["step_time_s"])


def _tiny_model():
    model = pt.hapi.Model(nn.Sequential(
        nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1)))
    model.prepare(pt.optimizer.SGD(learning_rate=0.01,
                                   parameters=model.parameters()),
                  nn.MSELoss())
    return model


def _tiny_data(n=4, bs=4):
    rng = np.random.RandomState(0)
    return [(rng.randn(bs, 8).astype(np.float32),
             rng.randn(bs, 1).astype(np.float32)) for _ in range(n)]


class TestStepTelemetry:
    def test_fit_records_and_scrapes(self):
        """Acceptance: a 2-layer Model.fit on CPU with telemetry enabled
        yields a Prometheus scrape with step-time and samples/sec, and a
        train loop runs with the exporter active (tier-1 smoke)."""
        reg = MetricsRegistry()
        tel = pt.callbacks.StepTelemetry(flops_per_sample=1000.0,
                                         registry=reg, peak=1e12)
        exp = MetricsExporter(0, reg)  # exporter live during training
        try:
            _tiny_model().fit(_tiny_data(), epochs=1, verbose=0,
                              callbacks=[tel])
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{exp.port}/metrics").read().decode()
        finally:
            exp.stop()
        assert "train_step_seconds" in text
        assert "train_samples_per_sec" in text
        assert "train_steps_total 4.0" in text
        stats = tel.last_stats
        assert stats["samples_per_sec"] > 0
        assert stats["mfu"] > 0
        assert stats["step_time_s"] >= stats["data_time_s"]

    def test_logs_injected_for_other_callbacks(self):
        seen = {}

        class Capture(pt.callbacks.Callback):
            def on_train_batch_end(self, step, logs=None):
                seen.update(logs or {})

        tel = pt.callbacks.StepTelemetry(registry=MetricsRegistry(), peak=0)
        _tiny_model().fit(_tiny_data(n=2), epochs=1, verbose=0,
                          callbacks=[tel, Capture()])
        assert "loss" in seen
        assert seen["samples_per_sec"] > 0
        assert seen["step_time_s"] > 0

    def test_flops_hint_from_network_attribute(self):
        model = _tiny_model()
        model.network.flops_per_sample = 500.0
        tel = pt.callbacks.StepTelemetry(registry=MetricsRegistry(),
                                         peak=1e12)
        model.fit(_tiny_data(n=2), epochs=1, verbose=0, callbacks=[tel])
        assert "mfu" in tel.last_stats


class TestCommTracing:
    def _mesh(self):
        import paddle_tpu.distributed as dist
        return dist.init_mesh({"dp": 8})

    def test_all_reduce_span_bytes_axes(self, tmp_path):
        """Acceptance: collective spans in the chrome trace carry bytes
        and group-axis attributes, in a dedicated lane with counters."""
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed import P
        mesh = self._mesh()

        @dist.spmd(mesh=mesh, in_specs=P("dp"), out_specs=P())
        def global_sum(x):
            return dist.all_reduce(x, group=dist.Group(("dp",)))

        with profiler.Profiler() as prof:
            out = global_sum(pt.to_tensor(np.ones((8, 4), np.float32)))
        assert np.allclose(out.numpy(), 8.0)
        comm = [e for e in prof.events if e.cat == "comm"]
        assert comm, "collective emitted no comm span"
        assert comm[0].args["bytes"] == 4 * 4  # per-shard (1,4) f32
        assert comm[0].args["axes"] == "dp"

        path = prof.export_chrome_tracing(str(tmp_path))
        data = profiler.load_profiler_result(path)
        spans = [e for e in data["traceEvents"]
                 if e.get("cat") == "comm" and e.get("ph") == "X"]
        assert spans and spans[0]["args"]["bytes"] == 16
        assert spans[0]["args"]["axes"] == "dp"
        counters = [e for e in data["traceEvents"] if e.get("ph") == "C"]
        assert counters and counters[-1]["args"]["bytes"] >= 16
        lanes = [e for e in data["traceEvents"]
                 if e.get("ph") == "M" and
                 e["args"].get("name") == "collectives"]
        assert lanes, "comm lane metadata missing"

    def test_counters_accumulate_per_op(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed import P
        mesh = self._mesh()
        before = comm_totals()

        @dist.spmd(mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
        def ring(x):
            y = dist.all_reduce(x, group=dist.Group(("dp",)))
            return dist.p2p_shift(y, group=dist.Group(("dp",)))

        ring(pt.to_tensor(np.ones((8, 2), np.float32)))
        after = comm_totals()
        assert after["comm_calls_total"] - before["comm_calls_total"] == 2
        assert after["comm_bytes_total"] - before["comm_bytes_total"] == 16
        reg = get_registry()
        assert reg.get("comm_bytes_total").value(
            op="p2p_shift", axes="dp") >= 8

    def test_send_recorded_before_raise(self, recorder_off):
        rec = flight_recorder.enable(capacity=8, use_native=False)
        import paddle_tpu.distributed as dist
        with pytest.raises(NotImplementedError):
            dist.send(pt.to_tensor(np.zeros((4,), np.float32)))
        names = [e["name"] for e in rec.events()]
        assert any(n.startswith("send@") for n in names)

    def test_payload_bytes(self):
        t = pt.to_tensor(np.zeros((3, 5), np.float32))
        assert payload_bytes(t) == 60
        assert payload_bytes([t, t]) == 120
        assert payload_bytes(None) == 0


class TestFlightRecorder:
    def test_ring_wraps_keeping_last(self, recorder_off):
        rec = flight_recorder.enable(capacity=4, use_native=False)
        for i in range(10):
            rec.record(flight_recorder.KIND_USER, f"e{i}", i, i + 1)
        names = [e["name"] for e in rec.events()]
        assert names == ["e6", "e7", "e8", "e9"]

    def test_native_ring_wraps(self, recorder_off):
        lib = profiler._NativeTracer.load()
        if lib is None or not hasattr(lib, "fr_start"):
            pytest.skip("native toolchain unavailable")
        rec = flight_recorder.enable(capacity=4, use_native=True)
        assert rec.native
        for i in range(10):
            rec.record(flight_recorder.KIND_COMM, f"n{i}", i, i + 1,
                       aux=i * 100)
        evs = rec.events()
        assert [e["name"] for e in evs] == ["n6", "n7", "n8", "n9"]
        assert evs[-1]["aux"] == 900
        assert evs[-1]["kind"] == "comm"

    def test_dump_from_native_ring(self, recorder_off, tmp_path,
                                   monkeypatch):
        """The production (toolchain-present) configuration: dump content
        comes out of the native fr_* ring, not the Python fallback."""
        lib = profiler._NativeTracer.load()
        if lib is None or not hasattr(lib, "fr_start"):
            pytest.skip("native toolchain unavailable")
        monkeypatch.setenv("PADDLE_TPU_TRACE_DIR", str(tmp_path))
        rec = flight_recorder.enable(capacity=16, use_native=True)
        assert rec.native
        a = pt.to_tensor(np.ones((2, 2), np.float32))
        pt.matmul(a, a)
        doc = json.load(open(rec.dump(reason="native-dump")))
        assert doc["native_ring"] is True
        names = [e["name"] for e in doc["events"]]
        assert "matmul" in names
        assert all({"kind", "name", "start_ns", "end_ns", "tid",
                    "aux"} <= set(e) for e in doc["events"])

    def test_ops_feed_recorder_without_profiler(self, recorder_off):
        rec = flight_recorder.enable(capacity=32, use_native=False)
        a = pt.to_tensor(np.ones((2, 2), np.float32))
        pt.matmul(a, a)
        names = [e["name"] for e in rec.events()]
        assert "matmul" in names
        assert profiler.Profiler().events == []  # profiler still untouched

    def test_dump_on_exception_with_rank(self, recorder_off, tmp_path,
                                         monkeypatch):
        """Acceptance: induced exception produces a postmortem JSON with
        the last recorded events and rank metadata."""
        monkeypatch.setenv("PADDLE_TPU_TRACE_DIR", str(tmp_path))
        monkeypatch.setenv("PADDLE_TRAINER_ID", "3")
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "8")
        rec = flight_recorder.enable(capacity=16, use_native=False)
        a = pt.to_tensor(np.ones((2, 2), np.float32))
        pt.add(a, a)
        try:
            raise ValueError("induced crash")
        except ValueError:
            sys.excepthook(*sys.exc_info())  # what an uncaught exc triggers
        dumps = [f for f in os.listdir(tmp_path)
                 if f.startswith("flight_recorder_rank3_")]
        assert dumps, "no postmortem written"
        doc = json.load(open(tmp_path / dumps[0]))
        assert doc["rank"] == 3 and doc["world_size"] == 8
        assert doc["reason"] == "unhandled ValueError"
        assert any(e["name"] == "add" for e in doc["events"])
        assert rec._dumped is not None

    def test_sigusr1_snapshot(self, recorder_off, tmp_path, monkeypatch):
        import signal
        import time
        monkeypatch.setenv("PADDLE_TPU_TRACE_DIR", str(tmp_path))
        flight_recorder.enable(capacity=8, use_native=False)
        flight_recorder.record(flight_recorder.KIND_USER, "marker", 0, 1)
        os.kill(os.getpid(), signal.SIGUSR1)
        time.sleep(0)  # bytecode checkpoint so the handler runs
        dumps = [f for f in os.listdir(tmp_path)
                 if f.startswith("flight_recorder_")]
        assert dumps
        doc = json.load(open(tmp_path / dumps[0]))
        assert doc["reason"] == "SIGUSR1"
        assert any(e["name"] == "marker" for e in doc["events"])

    def test_disable_restores_hooks(self, recorder_off):
        hook_before = sys.excepthook
        flight_recorder.enable(capacity=4, use_native=False)
        assert sys.excepthook is not hook_before
        flight_recorder.disable()
        assert sys.excepthook is hook_before
        assert flight_recorder.active() is None

    def test_env_gate(self, recorder_off, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_RECORDER", "0")
        assert flight_recorder.maybe_enable_from_env() is None
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_RECORDER", "64")
        rec = flight_recorder.maybe_enable_from_env()
        assert rec is not None and rec.capacity == 64

    def test_topology_in_dump(self, recorder_off, tmp_path, monkeypatch):
        import paddle_tpu.distributed as dist
        monkeypatch.setenv("PADDLE_TPU_TRACE_DIR", str(tmp_path))
        dist.init_mesh({"dp": 4, "mp": 2})
        rec = flight_recorder.enable(capacity=4, use_native=False)
        path = rec.dump(reason="topo")
        doc = json.load(open(path))
        assert doc["topology"] == {"dp": 4, "mp": 2}


class TestBenchEmit:
    def test_emit_metrics_schema(self, tmp_path):
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        try:
            import bench
        finally:
            sys.path.pop(0)
        out = tmp_path / "m.json"
        bench.emit_metrics(
            {"headline": {"metric": "mfu", "value": 63.3, "unit": "pct"},
             "detail": {"step_ms": 208.5, "config": {"layers": 8}}},
            str(out))
        doc = json.load(open(out))
        samples = {s["labels"]["key"]: s["value"]
                   for s in doc["bench_result"]["samples"]}
        assert samples["headline.value"] == 63.3
        assert samples["detail.step_ms"] == 208.5
        assert samples["detail.config.layers"] == 8
        assert "headline.metric" not in samples  # strings are not gauges
