"""Observability layer: metrics registry + exposition sinks, step
telemetry through Model.fit, collective-comm tracing, flight recorder
postmortems, bench.py metric emission (docs/OBSERVABILITY.md)."""
import json
import os
import sys
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.profiler as profiler
from paddle_tpu.observability import (
    MetricsRegistry, StepTimer, comm_totals, flight_recorder, get_registry,
    payload_bytes,
)
from paddle_tpu.observability.metrics import MetricsExporter


@pytest.fixture
def recorder_off():
    """Ensure the flight recorder never leaks across tests."""
    flight_recorder.disable()
    yield
    flight_recorder.disable()


class TestMetricsRegistry:
    def test_counter_labels(self):
        reg = MetricsRegistry()
        c = reg.counter("reqs_total", "requests")
        c.inc(2, route="/a")
        c.inc(route="/a")
        c.inc(5, route="/b")
        assert c.value(route="/a") == 3
        assert c.value(route="/b") == 5
        assert c.total() == 8

    def test_counter_monotonic(self):
        c = MetricsRegistry().counter("x")
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_gauge_set_inc(self):
        g = MetricsRegistry().gauge("temp")
        g.set(3.5, zone="hot")
        g.inc(0.5, zone="hot")
        g.dec(1.0, zone="hot")
        assert g.value(zone="hot") == pytest.approx(3.0)

    def test_histogram_buckets(self):
        h = MetricsRegistry().histogram("lat", buckets=[0.1, 1.0, 10.0])
        for v in (0.05, 0.5, 5.0, 50.0):
            h.observe(v)
        st = h.stats()
        assert st["count"] == 4
        assert st["sum"] == pytest.approx(55.55)

    def test_kind_conflict(self):
        reg = MetricsRegistry()
        reg.counter("m")
        with pytest.raises(ValueError):
            reg.gauge("m")

    def test_prometheus_text(self):
        reg = MetricsRegistry()
        reg.counter("hits_total", "hit count").inc(7, kind="a")
        reg.gauge("depth").set(2.5)
        reg.histogram("t", buckets=[1.0]).observe(0.5)
        text = reg.prometheus_text()
        assert "# TYPE hits_total counter" in text
        assert 'hits_total{kind="a"} 7.0' in text
        assert "depth 2.5" in text
        assert 't_bucket{le="1.0"} 1' in text
        assert 't_bucket{le="+Inf"} 1' in text
        assert "t_sum 0.5" in text and "t_count 1" in text

    def test_json_exposition(self):
        reg = MetricsRegistry()
        reg.counter("c").inc(3, op="x")
        reg.histogram("h", buckets=[1.0]).observe(0.5)
        doc = reg.to_json()
        assert doc["c"]["type"] == "counter"
        assert doc["c"]["samples"][0] == {"labels": {"op": "x"}, "value": 3.0}
        assert doc["h"]["samples"][0]["count"] == 1
        json.dumps(doc)  # fully serializable

    def test_reset_keeps_registrations(self):
        reg = MetricsRegistry()
        c = reg.counter("c")
        c.inc(3)
        reg.reset()
        assert c.value() == 0
        assert reg.get("c") is c

    def test_http_exporter(self):
        reg = MetricsRegistry()
        reg.gauge("scrape_me").set(42.0)
        exp = MetricsExporter(0, reg)  # ephemeral port
        try:
            base = f"http://127.0.0.1:{exp.port}"
            text = urllib.request.urlopen(f"{base}/metrics").read().decode()
            assert "scrape_me 42.0" in text
            doc = json.loads(urllib.request.urlopen(
                f"{base}/metrics.json").read().decode())
            assert doc["scrape_me"]["samples"][0]["value"] == 42.0
        finally:
            exp.stop()


class TestStepTimer:
    def test_decomposition_and_rates(self):
        reg = MetricsRegistry()
        timer = StepTimer(registry=reg, flops_per_sample=1e6, peak=1e9)
        timer.begin_step(data_time=0.25)
        stats = timer.end_step(samples=10, tokens=1000)
        assert stats["data_time_s"] == pytest.approx(0.25)
        assert stats["step_time_s"] > 0.25
        assert stats["compute_time_s"] >= 0
        assert stats["collective_time_s"] == 0.0
        assert stats["samples_per_sec"] == pytest.approx(
            10 / stats["step_time_s"])
        assert stats["tokens_per_sec"] == pytest.approx(
            1000 / stats["step_time_s"])
        assert stats["mfu"] == pytest.approx(
            10 * 1e6 / stats["step_time_s"] / 1e9)
        assert reg.counter("train_steps_total").value() == 1
        assert reg.get("train_step_seconds").stats()["count"] == 1

    def test_tokens_per_sample_hint(self):
        timer = StepTimer(registry=MetricsRegistry(), tokens_per_sample=128,
                          peak=0)
        timer.begin_step()
        stats = timer.end_step(samples=4)
        assert stats["tokens_per_sec"] == pytest.approx(
            4 * 128 / stats["step_time_s"])


def _tiny_model():
    model = pt.hapi.Model(nn.Sequential(
        nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1)))
    model.prepare(pt.optimizer.SGD(learning_rate=0.01,
                                   parameters=model.parameters()),
                  nn.MSELoss())
    return model


def _tiny_data(n=4, bs=4):
    rng = np.random.RandomState(0)
    return [(rng.randn(bs, 8).astype(np.float32),
             rng.randn(bs, 1).astype(np.float32)) for _ in range(n)]


class TestStepTelemetry:
    def test_fit_records_and_scrapes(self):
        """Acceptance: a 2-layer Model.fit on CPU with telemetry enabled
        yields a Prometheus scrape with step-time and samples/sec, and a
        train loop runs with the exporter active (tier-1 smoke)."""
        reg = MetricsRegistry()
        tel = pt.callbacks.StepTelemetry(flops_per_sample=1000.0,
                                         registry=reg, peak=1e12)
        exp = MetricsExporter(0, reg)  # exporter live during training
        try:
            _tiny_model().fit(_tiny_data(), epochs=1, verbose=0,
                              callbacks=[tel])
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{exp.port}/metrics").read().decode()
        finally:
            exp.stop()
        assert "train_step_seconds" in text
        assert "train_samples_per_sec" in text
        assert "train_steps_total 4.0" in text
        stats = tel.last_stats
        assert stats["samples_per_sec"] > 0
        assert stats["mfu"] > 0
        assert stats["step_time_s"] >= stats["data_time_s"]

    def test_logs_injected_for_other_callbacks(self):
        seen = {}

        class Capture(pt.callbacks.Callback):
            def on_train_batch_end(self, step, logs=None):
                seen.update(logs or {})

        tel = pt.callbacks.StepTelemetry(registry=MetricsRegistry(), peak=0)
        _tiny_model().fit(_tiny_data(n=2), epochs=1, verbose=0,
                          callbacks=[tel, Capture()])
        assert "loss" in seen
        assert seen["samples_per_sec"] > 0
        assert seen["step_time_s"] > 0

    def test_flops_hint_from_network_attribute(self):
        model = _tiny_model()
        model.network.flops_per_sample = 500.0
        tel = pt.callbacks.StepTelemetry(registry=MetricsRegistry(),
                                         peak=1e12)
        model.fit(_tiny_data(n=2), epochs=1, verbose=0, callbacks=[tel])
        assert "mfu" in tel.last_stats


class TestCommTracing:
    def _mesh(self):
        import paddle_tpu.distributed as dist
        return dist.init_mesh({"dp": 8})

    def test_all_reduce_span_bytes_axes(self, tmp_path):
        """Acceptance: collective spans in the chrome trace carry bytes
        and group-axis attributes, in a dedicated lane with counters."""
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed import P
        mesh = self._mesh()

        @dist.spmd(mesh=mesh, in_specs=P("dp"), out_specs=P())
        def global_sum(x):
            return dist.all_reduce(x, group=dist.Group(("dp",)))

        with profiler.Profiler() as prof:
            out = global_sum(pt.to_tensor(np.ones((8, 4), np.float32)))
        assert np.allclose(out.numpy(), 8.0)
        comm = [e for e in prof.events if e.cat == "comm"]
        assert comm, "collective emitted no comm span"
        assert comm[0].args["bytes"] == 4 * 4  # per-shard (1,4) f32
        assert comm[0].args["axes"] == "dp"

        path = prof.export_chrome_tracing(str(tmp_path))
        data = profiler.load_profiler_result(path)
        spans = [e for e in data["traceEvents"]
                 if e.get("cat") == "comm" and e.get("ph") == "X"]
        assert spans and spans[0]["args"]["bytes"] == 16
        assert spans[0]["args"]["axes"] == "dp"
        counters = [e for e in data["traceEvents"] if e.get("ph") == "C"]
        assert counters and counters[-1]["args"]["bytes"] >= 16
        lanes = [e for e in data["traceEvents"]
                 if e.get("ph") == "M" and
                 e["args"].get("name") == "collectives"]
        assert lanes, "comm lane metadata missing"

    def test_counters_accumulate_per_op(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed import P
        mesh = self._mesh()
        before = comm_totals()

        @dist.spmd(mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
        def ring(x):
            y = dist.all_reduce(x, group=dist.Group(("dp",)))
            return dist.p2p_shift(y, group=dist.Group(("dp",)))

        ring(pt.to_tensor(np.ones((8, 2), np.float32)))
        after = comm_totals()
        assert after["comm_calls_total"] - before["comm_calls_total"] == 2
        assert after["comm_bytes_total"] - before["comm_bytes_total"] == 16
        reg = get_registry()
        assert reg.get("comm_bytes_total").value(
            op="p2p_shift", axes="dp") >= 8

    def test_send_recorded_before_raise(self, recorder_off):
        rec = flight_recorder.enable(capacity=8, use_native=False)
        import paddle_tpu.distributed as dist
        with pytest.raises(NotImplementedError):
            dist.send(pt.to_tensor(np.zeros((4,), np.float32)))
        names = [e["name"] for e in rec.events()]
        assert any(n.startswith("send@") for n in names)

    def test_payload_bytes(self):
        t = pt.to_tensor(np.zeros((3, 5), np.float32))
        assert payload_bytes(t) == 60
        assert payload_bytes([t, t]) == 120
        assert payload_bytes(None) == 0


class TestFlightRecorder:
    def test_ring_wraps_keeping_last(self, recorder_off):
        rec = flight_recorder.enable(capacity=4, use_native=False)
        for i in range(10):
            rec.record(flight_recorder.KIND_USER, f"e{i}", i, i + 1)
        names = [e["name"] for e in rec.events()]
        assert names == ["e6", "e7", "e8", "e9"]

    def test_native_ring_wraps(self, recorder_off):
        lib = profiler._NativeTracer.load()
        if lib is None or not hasattr(lib, "fr_start"):
            pytest.skip("native toolchain unavailable")
        rec = flight_recorder.enable(capacity=4, use_native=True)
        assert rec.native
        for i in range(10):
            rec.record(flight_recorder.KIND_COMM, f"n{i}", i, i + 1,
                       aux=i * 100)
        evs = rec.events()
        assert [e["name"] for e in evs] == ["n6", "n7", "n8", "n9"]
        assert evs[-1]["aux"] == 900
        assert evs[-1]["kind"] == "comm"

    def test_dump_from_native_ring(self, recorder_off, tmp_path,
                                   monkeypatch):
        """The production (toolchain-present) configuration: dump content
        comes out of the native fr_* ring, not the Python fallback."""
        lib = profiler._NativeTracer.load()
        if lib is None or not hasattr(lib, "fr_start"):
            pytest.skip("native toolchain unavailable")
        monkeypatch.setenv("PADDLE_TPU_TRACE_DIR", str(tmp_path))
        rec = flight_recorder.enable(capacity=16, use_native=True)
        assert rec.native
        a = pt.to_tensor(np.ones((2, 2), np.float32))
        pt.matmul(a, a)
        doc = json.load(open(rec.dump(reason="native-dump")))
        assert doc["native_ring"] is True
        names = [e["name"] for e in doc["events"]]
        assert "matmul" in names
        assert all({"kind", "name", "start_ns", "end_ns", "tid",
                    "aux"} <= set(e) for e in doc["events"])

    def test_ops_feed_recorder_without_profiler(self, recorder_off):
        rec = flight_recorder.enable(capacity=32, use_native=False)
        a = pt.to_tensor(np.ones((2, 2), np.float32))
        pt.matmul(a, a)
        names = [e["name"] for e in rec.events()]
        assert "matmul" in names
        assert profiler.Profiler().events == []  # profiler still untouched

    def test_dump_on_exception_with_rank(self, recorder_off, tmp_path,
                                         monkeypatch):
        """Acceptance: induced exception produces a postmortem JSON with
        the last recorded events and rank metadata."""
        monkeypatch.setenv("PADDLE_TPU_TRACE_DIR", str(tmp_path))
        monkeypatch.setenv("PADDLE_TRAINER_ID", "3")
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "8")
        rec = flight_recorder.enable(capacity=16, use_native=False)
        a = pt.to_tensor(np.ones((2, 2), np.float32))
        pt.add(a, a)
        try:
            raise ValueError("induced crash")
        except ValueError:
            sys.excepthook(*sys.exc_info())  # what an uncaught exc triggers
        dumps = [f for f in os.listdir(tmp_path)
                 if f.startswith("flight_recorder_rank3_")]
        assert dumps, "no postmortem written"
        doc = json.load(open(tmp_path / dumps[0]))
        assert doc["rank"] == 3 and doc["world_size"] == 8
        assert doc["reason"] == "unhandled ValueError"
        assert any(e["name"] == "add" for e in doc["events"])
        assert rec._dumped is not None

    def test_sigusr1_snapshot(self, recorder_off, tmp_path, monkeypatch):
        import signal
        import time
        monkeypatch.setenv("PADDLE_TPU_TRACE_DIR", str(tmp_path))
        flight_recorder.enable(capacity=8, use_native=False)
        flight_recorder.record(flight_recorder.KIND_USER, "marker", 0, 1)
        os.kill(os.getpid(), signal.SIGUSR1)
        time.sleep(0)  # bytecode checkpoint so the handler runs
        dumps = [f for f in os.listdir(tmp_path)
                 if f.startswith("flight_recorder_")]
        assert dumps
        doc = json.load(open(tmp_path / dumps[0]))
        assert doc["reason"] == "SIGUSR1"
        assert any(e["name"] == "marker" for e in doc["events"])

    def test_disable_restores_hooks(self, recorder_off):
        hook_before = sys.excepthook
        flight_recorder.enable(capacity=4, use_native=False)
        assert sys.excepthook is not hook_before
        flight_recorder.disable()
        assert sys.excepthook is hook_before
        assert flight_recorder.active() is None

    def test_env_gate(self, recorder_off, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_RECORDER", "0")
        assert flight_recorder.maybe_enable_from_env() is None
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_RECORDER", "64")
        rec = flight_recorder.maybe_enable_from_env()
        assert rec is not None and rec.capacity == 64

    def test_topology_in_dump(self, recorder_off, tmp_path, monkeypatch):
        import paddle_tpu.distributed as dist
        monkeypatch.setenv("PADDLE_TPU_TRACE_DIR", str(tmp_path))
        dist.init_mesh({"dp": 4, "mp": 2})
        rec = flight_recorder.enable(capacity=4, use_native=False)
        path = rec.dump(reason="topo")
        doc = json.load(open(path))
        assert doc["topology"] == {"dp": 4, "mp": 2}


class TestBenchEmit:
    def test_emit_metrics_schema(self, tmp_path):
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        try:
            import bench
        finally:
            sys.path.pop(0)
        out = tmp_path / "m.json"
        bench.emit_metrics(
            {"headline": {"metric": "mfu", "value": 63.3, "unit": "pct"},
             "detail": {"step_ms": 208.5, "config": {"layers": 8}}},
            str(out))
        doc = json.load(open(out))
        samples = {s["labels"]["key"]: s["value"]
                   for s in doc["bench_result"]["samples"]}
        assert samples["headline.value"] == 63.3
        assert samples["detail.step_ms"] == 208.5
        assert samples["detail.config.layers"] == 8
        assert "headline.metric" not in samples  # strings are not gauges


# ======================= PR 6: performance attribution layer ================

class TestTraceLayer:
    """Per-rank trace files + cross-rank merge (ISSUE 6 tentpole)."""

    @staticmethod
    def _write_rank(tmp_path, rank, skew_ns=0, steps=(1, 2)):
        from paddle_tpu.observability import trace
        w = trace.TraceWriter(
            str(tmp_path / f"trace_rank{rank}_{rank}.jsonl"), rank=rank)
        base = 10_000_000_000
        for sid in steps:
            s = base + sid * 100_000_000
            w.span("step", "train_step", s, s + 50_000_000 + skew_ns,
                   args={"step": sid})
        w.span("comm", "all_reduce@dp", base, base + 1_000_000,
               args={"bytes": 4096, "axes": "dp", "exposed_s": 0.0005,
                     "overlapped_s": 0.0005})
        w.close()
        return w

    def test_merge_two_ranks_chrome_and_skew(self, tmp_path):
        from paddle_tpu.observability import trace
        self._write_rank(tmp_path, 0, skew_ns=0)
        self._write_rank(tmp_path, 1, skew_ns=5_000_000)  # 5ms straggler
        summary = trace.merge(str(tmp_path))
        assert summary["ranks"] == [0, 1]
        assert summary["steps_compared"] == 2
        # rank 1 finishes every step ~5ms late: it is the straggler and
        # the end-spread reflects the injected skew (anchor sampling
        # jitter between the two writers stays well under a millisecond)
        assert summary["straggler_counts"] == {"1": 2}
        assert 4_000_000 < summary["skew"]["step_end_spread_ns"]["max"] \
            < 6_000_000
        # comm rollup aggregates across ranks
        assert summary["comm_by_axes"]["dp"]["calls"] == 2
        assert summary["comm_by_axes"]["dp"]["bytes"] == 8192
        # one chrome trace, time-ordered, one process lane per rank
        doc = json.load(open(summary["out_trace"]))
        evs = [e for e in doc["traceEvents"] if e["ph"] in ("X", "i")]
        assert [e["ts"] for e in evs] == sorted(e["ts"] for e in evs)
        assert {e["pid"] for e in evs} == {0, 1}
        names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "M"}
        assert names == {"process_name"}
        assert os.path.exists(summary["out_summary"])

    def test_merge_aligns_skewed_clocks(self, tmp_path):
        """Two ranks whose perf_counter epochs differ wildly but whose
        unix anchors agree must land on one clock."""
        from paddle_tpu.observability import trace
        for rank, (perf0, unix0) in enumerate(
                [(1_000, 5_000_000_000), (999_000_000, 5_000_000_000)]):
            p = tmp_path / f"trace_rank{rank}_{rank}.jsonl"
            with open(p, "w") as f:
                f.write(json.dumps(
                    {"type": "header", "version": 1, "rank": rank,
                     "clock": {"perf_ns": perf0, "unix_ns": unix0}}) + "\n")
                # same wall-clock instant on both ranks' local clocks
                f.write(json.dumps(
                    {"type": "span", "cat": "step", "name": "train_step",
                     "ts": perf0 + 7_000_000, "dur": 1_000_000,
                     "tid": 0, "args": {"step": 1}}) + "\n")
        summary = trace.merge(str(tmp_path))
        assert summary["skew"]["step_end_spread_ns"]["max"] == 0
        doc = json.load(open(summary["out_trace"]))
        ts = [e["ts"] for e in doc["traceEvents"] if e["ph"] == "X"]
        assert ts[0] == ts[1]

    def test_merge_relaunched_rank_gets_own_lane(self, tmp_path):
        """Crash + relaunch leaves TWO files for one rank (the
        postmortem case) — they must stay separate lanes, not clobber
        each other's step times."""
        from paddle_tpu.observability import trace
        for pid, steps in ((100, (1, 2)), (200, (2, 3))):
            p = tmp_path / f"trace_rank0_{pid}.jsonl"
            with open(p, "w") as f:
                f.write(json.dumps(
                    {"type": "header", "version": 1, "rank": 0,
                     "pid": pid,
                     "clock": {"perf_ns": 0, "unix_ns": 0}}) + "\n")
                for sid in steps:
                    f.write(json.dumps(
                        {"type": "span", "cat": "step",
                         "name": "train_step", "ts": sid * 100_000_000,
                         "dur": 50_000_000, "tid": 0,
                         "args": {"step": sid}}) + "\n")
        self._write_rank(tmp_path, 1, skew_ns=5_000_000)
        summary = trace.merge(str(tmp_path))
        assert summary["ranks"] == [0, 1]          # unique ranks
        assert len(summary["files"]) == 3          # but three lanes
        assert set(summary["clock_offsets_ns"]) == \
            {"0:100", "0:200", "1"}
        doc = json.load(open(summary["out_trace"]))
        lanes = {e["pid"] for e in doc["traceEvents"] if e["ph"] == "X"}
        assert len(lanes) == 3                     # one chrome lane each
        # step 2 exists in both rank-0 incarnations AND rank 1: the
        # spread must span all three lanes, not a clobbered pair
        assert summary["steps_compared"] >= 1
        assert "2" in summary["per_step"]

    def test_merge_skips_torn_tail(self, tmp_path):
        from paddle_tpu.observability import trace
        w = self._write_rank(tmp_path, 0)
        with open(w.path, "a") as f:
            f.write('{"type": "span", "cat": "step", "na')  # crash mid-line
        summary = trace.merge(str(tmp_path))
        assert summary["events"] == 3

    def test_module_seam_and_env_gate(self, tmp_path, monkeypatch):
        from paddle_tpu.observability import trace
        trace.disable()
        trace.span("phase", "ignored", 0, 1)  # off: must be a no-op
        monkeypatch.setenv("PADDLE_TPU_TRACE_SPANS", str(tmp_path))
        try:
            w = trace.maybe_enable_from_env()
            assert w is not None
            trace.span("phase", "visible", 100, 200, args={"k": 1})
            trace.mark("phase", "point", ts_ns=150)
            trace.disable()
            lines = [json.loads(ln) for ln in open(w.path)]
            assert lines[0]["type"] == "header"
            assert [e["name"] for e in lines[1:]] == ["visible", "point"]
        finally:
            trace.disable()


class TestExposureAccounting:
    """comm_scope wall time classified overlapped-vs-exposed (ISSUE 6)."""

    def test_inside_compute_scope_counts_overlapped(self):
        import time as _time
        from paddle_tpu.observability import comm, compute_scope, comm_scope
        t0 = comm_totals()
        with compute_scope():
            with comm_scope("all_reduce", ["dp"], nbytes=64):
                _time.sleep(0.01)
        t1 = comm_totals()
        overlapped = t1["comm_overlapped_seconds_total"] - \
            t0["comm_overlapped_seconds_total"]
        exposed = t1["comm_exposed_seconds_total"] - \
            t0["comm_exposed_seconds_total"]
        assert overlapped >= 0.009
        assert exposed == pytest.approx(0.0, abs=1e-4)

    def test_outside_compute_scope_counts_exposed(self):
        import time as _time
        from paddle_tpu.observability import comm_scope
        t0 = comm_totals()
        with comm_scope("all_gather", ["mp"], nbytes=64):
            _time.sleep(0.01)
        t1 = comm_totals()
        exposed = t1["comm_exposed_seconds_total"] - \
            t0["comm_exposed_seconds_total"]
        overlapped = t1["comm_overlapped_seconds_total"] - \
            t0["comm_overlapped_seconds_total"]
        assert exposed >= 0.009
        assert overlapped == pytest.approx(0.0, abs=1e-4)

    def test_partial_overlap_splits(self):
        """A span half inside a compute region splits its time."""
        import time as _time
        from paddle_tpu.observability.comm import (_compute, _emit,
                                                   comm_totals as ct)
        t0 = ct()
        tok = _compute.begin()
        _time.sleep(0.01)
        _compute.end(tok)
        import time
        now = time.perf_counter_ns()
        # synthetic span covering the compute interval plus 10ms after
        _emit("all_reduce", "dp", 0, now - 20_000_000, now)
        t1 = ct()
        ov = t1["comm_overlapped_seconds_total"] - \
            t0["comm_overlapped_seconds_total"]
        ex = t1["comm_exposed_seconds_total"] - \
            t0["comm_exposed_seconds_total"]
        assert 0.005 < ov < 0.015
        assert 0.005 < ex < 0.015
        assert ov + ex == pytest.approx(0.02, abs=1e-6)

    def test_overlapping_compute_regions_measure_union(self):
        """Two compute regions covering the SAME half of a comm span
        must credit that half once — summing intersections would call
        the span fully overlapped."""
        from paddle_tpu.observability.comm import _ComputeTracker
        tr = _ComputeTracker()
        tr._closed.append((0, 50))
        tr._closed.append((10, 50))      # nested/concurrent region
        assert tr.overlap_ns(0, 100) == 50
        tr._closed.append((60, 70))      # disjoint second region
        assert tr.overlap_ns(0, 100) == 60

    def test_step_timer_reports_exposed_share(self):
        import time as _time
        from paddle_tpu.observability import comm_scope
        timer = StepTimer(registry=MetricsRegistry(), peak=0)
        timer.begin_step()
        with comm_scope("all_reduce", ["dp"], nbytes=8):
            _time.sleep(0.005)
        stats = timer.end_step(samples=1)
        assert stats["exposed_collective_time_s"] >= 0.004
        assert stats["collective_time_s"] >= 0.004

    def test_train_step_runs_under_compute_scope(self):
        """The compiled TrainStep call is a compute region: a collective
        emitted during it (trace-time or bucketed-async) counts
        overlapped, which is the attribution signal the all-reduce
        bucketing work will optimize against."""
        from paddle_tpu.jit.train_step import TrainStep
        from paddle_tpu.observability.comm import _compute

        seen = []
        net = nn.Linear(4, 4)
        opt = pt.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())

        def loss_fn(m, x):
            # executes at trace time, inside the compiled call's scope
            seen.append(len(_compute._open) > 0)
            return pt.ops.mean(m(x))

        step = TrainStep(net, loss_fn, opt)
        step(pt.to_tensor(np.ones((2, 4), np.float32)))
        assert seen and seen[0]
        assert not _compute._open  # scope closed after the call


class TestMetricsCardinalityGuard:
    def test_cap_folds_into_overflow(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_METRICS_MAX_LABELSETS", "5")
        reg = MetricsRegistry()
        c = reg.counter("explode_total", "per-request labels gone wrong")
        with pytest.warns(RuntimeWarning, match="label-cardinality cap"):
            for i in range(50):
                c.inc(1, req_id=str(i))
        # bounded: 5 admitted + the one overflow series
        assert len(c._samples) == 6
        assert c.total() == 50  # nothing dropped, overflow accumulates
        from paddle_tpu.observability.metrics import OVERFLOW_KEY
        assert c._samples[OVERFLOW_KEY] == 45
        # existing label sets keep incrementing normally past the cap
        c.inc(1, req_id="0")
        assert c.value(req_id="0") == 2

    def test_warning_fires_once_per_family(self, monkeypatch):
        import warnings as _warnings
        monkeypatch.setenv("PADDLE_TPU_METRICS_MAX_LABELSETS", "2")
        g = MetricsRegistry().gauge("g")
        with _warnings.catch_warnings(record=True) as rec:
            _warnings.simplefilter("always")
            for i in range(10):
                g.set(1.0, k=str(i))
        assert sum("label-cardinality" in str(w.message)
                   for w in rec) == 1

    def test_histogram_guarded_too(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_METRICS_MAX_LABELSETS", "3")
        h = MetricsRegistry().histogram("h", buckets=[1.0])
        with pytest.warns(RuntimeWarning):
            for i in range(9):
                h.observe(0.5, k=str(i))
        assert len(h._samples) == 4
        total = sum(s["count"] for s in h._samples.values())
        assert total == 9


class TestExporterConcurrency:
    def test_scrape_during_mutation_and_registration(self):
        """Hammer: scrapes must stay consistent (and not raise) while
        other threads increment labeled counters, observe histograms,
        and register brand-new families."""
        import re
        import threading

        reg = MetricsRegistry()
        stop = threading.Event()
        errs = []

        def mutate(tid):
            try:
                c = reg.counter("hammer_total")
                h = reg.histogram("hammer_seconds", buckets=[0.5, 1.0])
                i = 0
                while not stop.is_set():
                    c.inc(1, thread=str(tid), bucket=str(i % 7))
                    h.observe(0.25, thread=str(tid))
                    # new families mid-scrape — bounded, or every scrape
                    # grows O(iterations) and this one test eats minutes
                    # of the tier-1 budget on a 1-CPU box
                    if i % 50 == 0 and i < 1000:
                        reg.gauge(f"hammer_new_{tid}_{i}").set(1.0)
                    i += 1
            except Exception as e:  # pragma: no cover - the bug we hunt
                errs.append(e)

        threads = [threading.Thread(target=mutate, args=(t,))
                   for t in range(3)]
        for t in threads:
            t.start()
        t0 = time.monotonic()
        try:
            for _ in range(200):
                if time.monotonic() - t0 > 20:
                    break  # the race reproduces in seconds; stay cheap
                text = reg.prometheus_text()
                doc = reg.to_json()
                # histogram internal consistency: the +Inf bucket of each
                # series equals its _count line (torn reads break this)
                for m in re.finditer(
                        r'hammer_seconds_bucket\{le="\+Inf",'
                        r'thread="(\d+)"\} (\d+)', text):
                    tid, inf = m.group(1), int(m.group(2))
                    cnt = re.search(
                        r'hammer_seconds_count\{thread="%s"\} (\d+)' % tid,
                        text)
                    assert cnt and int(cnt.group(1)) == inf
                for fam in doc.values():
                    for s in fam["samples"]:
                        if "buckets" in s:
                            assert max(s["buckets"].values(),
                                       default=0) <= s["count"]
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5)
        assert not errs


class TestFlightRecorderCkptDataKinds:
    def test_checkpoint_commit_and_restore_events(self, recorder_off,
                                                  tmp_path):
        from paddle_tpu.checkpoint import CheckpointManager
        flight_recorder.enable(capacity=64, use_native=False)
        mgr = CheckpointManager(str(tmp_path))
        state = {"w": pt.to_tensor(np.ones((2, 2), np.float32))}
        mgr.save(3, state, async_=False)
        mgr.restore(3)
        mgr.close()
        evs = flight_recorder.active().events()
        kinds = [(e["kind"], e["name"]) for e in evs]
        assert ("ckpt", "commit:step_3") in kinds
        assert ("ckpt", "restore:step_3") in kinds
        commit = next(e for e in evs if e["name"] == "commit:step_3")
        assert commit["aux"] == 3 and commit["args"]["bytes"] > 0

    def test_data_pipeline_commit_events(self, recorder_off):
        from paddle_tpu.data import DataPipeline
        flight_recorder.enable(capacity=64, use_native=False)
        docs = [np.arange(1, 9, dtype=np.int32) for _ in range(8)]
        pipe = DataPipeline(docs, batch_size=2, seq_len=8, pack=True,
                            base_seed=1, shuffle=False, drop_last=True)
        n = sum(1 for _ in pipe)
        assert n > 0
        evs = [e for e in flight_recorder.active().events()
               if e["kind"] == "data"]
        assert len(evs) == n
        assert evs[-1]["args"]["step"] == n
        assert "epoch" in evs[-1]["args"]
        # the NAME carries step+epoch too — the native ring drops args,
        # and the postmortem must show the data position either way
        assert evs[-1]["name"] == \
            f"commit:step_{n}@epoch_{evs[-1]['args']['epoch']}"
