"""Block-granular prefix cache + tensor-parallel serving (ISSUE 15).

Coverage contract: BlockAllocator reclaimable-tier invariants (park on
last free, LRU eviction order + index callback, resurrection via
``reuse_cached``, capacity accounting incl. ``assert_no_leaks``),
chain-hash semantics, PrefixCache match/register incl. the
fully-cached ``len−1`` COW cap, and engine integration — shared-prefix
greedy streams bit-identical cache-on vs cache-off (the cache-off
engine is the parity oracle), copy-on-write divergence, abort while a
cached block is shared live, preemption re-admitting THROUGH the cache
(recompute == uncached tail only), and mp=2 tensor-parallel token
parity against the single-device stream over the CPU 8-virtual-device
mesh (tests/conftest.py forces ``--xla_force_host_platform_device_count=8``).
"""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import ServingEngine
from paddle_tpu.serving.kv_cache import (BlockAllocator, PrefixCache,
                                         chain_hash)


def _tiny(seed=0, tensor_parallel=False):
    pt.seed(seed)
    m = LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, tie_word_embeddings=True,
        tensor_parallel=tensor_parallel))
    m.eval()
    return m


def _eager_continuation(model, prompt, max_new_tokens):
    out = model.generate(pt.to_tensor(np.asarray(prompt)[None, :]),
                         max_new_tokens=max_new_tokens,
                         temperature=0.0).numpy()[0]
    return [int(t) for t in out[len(prompt):]]


# ---------------- chain hashing ----------------------------------------------
def test_chain_hash_commits_to_whole_prefix():
    h1 = chain_hash(None, [1, 2, 3, 4])
    assert h1 == chain_hash(None, [1, 2, 3, 4]) and len(h1) == 16
    assert h1 != chain_hash(None, [1, 2, 3, 5])
    # same block content under a different parent → different digest:
    # a block's identity includes every token before it
    assert chain_hash(h1, [5, 6]) != chain_hash(chain_hash(None, [9]),
                                                [5, 6])


# ---------------- allocator reclaimable tier ---------------------------------
def test_reclaimable_park_resurrect_and_accounting():
    a = BlockAllocator(4)
    b1, b2 = a.allocate(2)
    a.mark_cached(b1, b"k1")
    a.free([b1])                       # cached: parks, doesn't free
    a.free([b2])                       # uncached: straight to free list
    assert a.num_reclaimable() == 1 and a.num_free() == 3
    assert a.blocks_in_use() == 0
    assert a.can_allocate(4)           # reclaimable counts as capacity
    a.assert_no_leaks()                # parked blocks are accounted
    # resurrection: a parked block comes back live at refcount 1
    assert a.reuse_cached(b1)
    assert a.refcount(b1) == 1 and a.num_reclaimable() == 0
    # live cached block shares by incref through the same API
    assert a.reuse_cached(b1) and a.refcount(b1) == 2
    a.free([b1]), a.free([b1])
    a.assert_no_leaks()


def test_reclaimable_lru_eviction_order_and_callback():
    a = BlockAllocator(3)
    evicted = []
    a._evict_cb = lambda b, k: evicted.append((b, k))
    blocks = a.allocate(3)
    for i, b in enumerate(blocks):
        a.mark_cached(b, bytes([i]) * 16)
    a.free([blocks[0]])                # parked first → LRU-oldest
    a.free([blocks[2]])
    a.free([blocks[1]])
    got = a.allocate(2)                # free list empty: must evict
    assert evicted == [(blocks[0], bytes([0]) * 16),
                       (blocks[2], bytes([2]) * 16)]   # LRU order
    assert not a.is_cached(blocks[0]) and a.is_cached(blocks[1])
    assert a.reuse_cached(blocks[0]) is False   # evicted: gone
    a.free(got)            # blocks[1] is already parked at refcount 0
    a.assert_no_leaks()


# ---------------- PrefixCache unit -------------------------------------------
def test_prefix_cache_match_register_and_cow_cap():
    a = BlockAllocator(8)
    pc = PrefixCache(a, block_size=4)
    blocks = a.allocate(2)
    d0 = chain_hash(None, [1, 2, 3, 4])
    d1 = chain_hash(d0, [5, 6, 7, 8])
    pc.register(d0, blocks[0])
    pc.register(d1, blocks[1])
    a.free(blocks)                     # registered → both park
    # partial tail: only full, chain-linked blocks match
    got, digests = pc.match([1, 2, 3, 4, 5, 6, 7, 8, 9])
    assert got == blocks and digests == [d0, d1]
    assert a.refcount(blocks[0]) == 1  # match CLAIMS the blocks
    a.free(blocks)
    # divergence in the second block stops the walk after the first
    got2, _ = pc.match([1, 2, 3, 4, 9, 9, 9, 9, 1])
    assert got2 == [blocks[0]]
    a.free(got2)
    assert pc.stats()["lookups"] == 2 and pc.stats()["hits"] == 2
    a.assert_no_leaks()


# ---------------- engine integration -----------------------------------------
BS = 4


@pytest.fixture(scope="module")
def model():
    return _tiny(11)


@pytest.fixture(scope="module")
def eng_on(model):
    return ServingEngine(model, max_batch=4, max_blocks=32, block_size=BS,
                         prefill_chunk=4, prefix_cache=True)


def test_shared_prefix_bit_parity_cache_on_vs_off(model, eng_on):
    """The tentpole parity oracle: identical greedy streams with the
    cache on and off over shared-prefix traffic, with the cache-on run
    actually hitting."""
    eng_off = ServingEngine(model, max_batch=4, max_blocks=32,
                            block_size=BS, prefill_chunk=4,
                            prefix_cache=False)
    assert eng_off.stats()["prefix_cache"] is None
    rng = np.random.RandomState(0)
    pfx = [int(t) for t in rng.randint(1, 128, 12)]
    prompts = [pfx + [int(t) for t in rng.randint(1, 128, n)]
               for n in (3, 5, 2)]
    streams = {}
    for name, eng in (("on", eng_on), ("off", eng_off)):
        # first request runs alone so its blocks COMMIT before the rest
        # admit (registration happens after the step that writes a
        # block's last token) — the bench's warmup, in miniature
        h0 = eng.submit(prompts[0], max_new_tokens=6)
        eng.run_until_idle()
        handles = [eng.submit(p, max_new_tokens=6) for p in prompts[1:]]
        eng.run_until_idle()
        streams[name] = [h.result(30)["token_ids"]
                         for h in [h0] + handles]
        eng.cache.allocator.assert_no_leaks()
    assert streams["on"] == streams["off"]
    pc = eng_on.stats()["prefix_cache"]
    assert pc["hits"] >= 2 and pc["hit_tokens"] >= 2 * 12
    # headroom splits: free + reclaimable == allocatable headroom
    st = eng_on.stats()
    assert st["kv_headroom"] == pytest.approx(
        st["kv_free_fraction"] + st["kv_reclaimable_fraction"])
    assert st["kv_blocks_reclaimable"] > 0     # warm cache parked


def test_fully_cached_prompt_cow_lifecycle(model, eng_on):
    """Resubmitting an identical block-aligned prompt is the COW
    corner: every token is cached, the cap re-prefills exactly one, and
    the copied block is private (stream still bit-exact)."""
    rng = np.random.RandomState(1)
    prompt = [int(t) for t in rng.randint(1, 128, 3 * BS)]  # aligned
    base = _eager_continuation(model, prompt, 5)
    h1 = eng_on.submit(prompt, max_new_tokens=5)
    eng_on.run_until_idle()
    assert h1.result(30)["token_ids"] == base
    h2 = eng_on.submit(prompt, max_new_tokens=5)
    eng_on.run_until_idle()
    assert h2.result(30)["token_ids"] == base
    r = h2._req
    assert r.cached_tokens_total == len(prompt) - 1   # the len−1 cap
    assert r.prefilled_tokens == \
        r.admitted_pending_total - r.cached_tokens_total
    assert r.cow_src is None                          # copy released
    eng_on.cache.allocator.assert_no_leaks()


def test_mid_block_divergence_matches_cold_runs(model, eng_on):
    """Two prompts sharing two full blocks then diverging inside the
    third: the chain hash stops the match at the shared boundary and
    both streams equal their solo cold baselines."""
    rng = np.random.RandomState(2)
    pfx = [int(t) for t in rng.randint(1, 128, 2 * BS)]
    pa = pfx + [int(t) for t in rng.randint(1, 128, 3)]
    pb = pfx + [int(t) for t in rng.randint(1, 128, 3)]
    assert pa[2 * BS:] != pb[2 * BS:]
    ha = eng_on.submit(pa, max_new_tokens=4)
    eng_on.run_until_idle()
    hb = eng_on.submit(pb, max_new_tokens=4)
    eng_on.run_until_idle()
    assert ha.result(30)["token_ids"] == _eager_continuation(model, pa, 4)
    assert hb.result(30)["token_ids"] == _eager_continuation(model, pb, 4)
    # b matched exactly the shared full blocks, recomputed its own tail
    assert hb._req.cached_tokens_total == 2 * BS
    eng_on.cache.allocator.assert_no_leaks()


def test_abort_while_cached_block_shared(model, eng_on):
    """Aborting one of two requests sharing cached blocks must drop only
    its references: the survivor finishes bit-exact and the blocks
    return to the reclaimable tier, not the free list."""
    rng = np.random.RandomState(3)
    pfx = [int(t) for t in rng.randint(1, 128, 3 * BS)]
    warm = eng_on.submit(pfx + [1], max_new_tokens=2)
    eng_on.run_until_idle()
    warm.result(30)
    hb = eng_on.submit(pfx + [5, 6], max_new_tokens=4)
    hc = eng_on.submit(pfx + [7, 8], max_new_tokens=4)
    # admit both (no model step yet): they claim the same cached blocks
    eng_on.scheduler._admit()
    shared = hb._req.block_ids[:3]
    assert shared and shared == hc._req.block_ids[:3]
    alloc = eng_on.cache.allocator
    assert all(alloc.refcount(b) == 2 for b in shared)
    assert eng_on.abort(hb.req_id, reason="test")
    assert all(alloc.refcount(b) == 1 for b in shared)  # survivor holds
    eng_on.run_until_idle()
    assert hc.result(30)["token_ids"] == \
        _eager_continuation(model, pfx + [7, 8], 4)
    assert all(alloc.is_cached(b) for b in shared)      # parked again
    alloc.assert_no_leaks()


def test_preemption_readmits_through_cache(model):
    """Deterministic preempt→readmit: the committed blocks park, the
    readmission match claims them back, and the recompute prefills
    ONLY the uncached tail (the ISSUE 15 preemption satellite, in
    isolation from victim-selection timing)."""
    eng = ServingEngine(model, max_batch=2, max_blocks=32, block_size=BS,
                        prefill_chunk=4, prefix_cache=True)
    rng = np.random.RandomState(4)
    prompt = [int(t) for t in rng.randint(1, 128, 10)]
    h = eng.submit(prompt, max_new_tokens=8)
    while len(h._req.generated) < 4:
        assert eng.step()
    committed = h._req.committed_blocks
    assert committed >= 3                    # 12+ tokens committed
    eng.scheduler.preempt(h._req)
    assert eng.cache.allocator.num_reclaimable() >= committed
    eng.run_until_idle()
    assert h.result(30)["token_ids"] == \
        _eager_continuation(model, prompt, 8)
    r = h._req
    assert r.preemptions == 1
    assert r.cached_tokens_total == committed * BS   # tail-only recompute
    assert r.prefilled_tokens == \
        r.admitted_pending_total - r.cached_tokens_total
    eng.cache.allocator.assert_no_leaks()


def test_tensor_parallel_mp2_token_parity():
    """mp=2 over two of the 8 CPU virtual devices: Megatron-sharded
    weights + KV pools, ONE compiled SPMD step, greedy stream
    bit-identical to the single-device (eager) stream."""
    import jax

    from paddle_tpu.distributed import get_mesh, init_mesh, set_mesh

    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices")
    prev = get_mesh()
    try:
        # the model must be BUILT under the mesh: the Megatron layers
        # stamp their sharding specs against it at construction
        mesh = init_mesh({"mp": 2}, devices=jax.devices()[:2])
        model = _tiny(12, tensor_parallel=True)
        eng = ServingEngine(model, max_batch=2, max_blocks=16,
                            block_size=BS, prefill_chunk=4, mesh=mesh)
        assert eng.stats()["tensor_parallel"] == 2
        rng = np.random.RandomState(5)
        prompts = [[int(t) for t in rng.randint(1, 128, n)]
                   for n in (9, 6)]
        handles = [eng.submit(p, max_new_tokens=6) for p in prompts]
        eng.run_until_idle()
        for h, p in zip(handles, prompts):
            assert h.result(60)["token_ids"] == \
                _eager_continuation(model, p, 6)
        assert eng.step_traces == 1
        eng.cache.allocator.assert_no_leaks()
    finally:
        set_mesh(prev)
