"""SPMD (collective) pipeline: the compiled-ppermute engine that replaces
single-controller device_put hops so pipeline stages can span hosts
(reference counterpart: fleet/meta_parallel/pp_utils/p2p_communication.py
send_v2/recv_v2 + pipeline_parallel.py 1F1B/interleave)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.distributed as dist
import paddle_tpu.distributed.fleet as fleet
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt


@pytest.fixture()
def pp_mesh():
    return dist.init_mesh({"dp": 2, "pp": 4})


def _mlp_chunks(rng, C, d=8):
    Ws = rng.randn(C, d, d).astype(np.float32) * 0.3
    bs = rng.randn(C, d).astype(np.float32) * 0.1
    return Ws, bs


def _body(p, x):
    return jnp.tanh(x @ p["W"] + p["b"])


@pytest.mark.parametrize("v,M", [(1, 4), (2, 4), (1, 6), (2, 6), (3, 8)])
def test_forward_parity_vs_sequential(pp_mesh, v, M):
    """Any micro-count (no M % S constraint), any virtual-stage depth:
    the pipelined result equals running the chunks sequentially."""
    S = 4
    rng = np.random.RandomState(v * 10 + M)
    Ws, bs = _mlp_chunks(rng, v * S)
    params = {"W": jnp.asarray(Ws).reshape(v, S, 8, 8),
              "b": jnp.asarray(bs).reshape(v, S, 8)}
    xs = jnp.asarray(rng.randn(M, 2, 8).astype(np.float32))
    out = fleet.pipeline_spmd(_body, params, xs, mesh=pp_mesh,
                              num_virtual_stages=v)
    ref = np.asarray(xs)
    for c in range(v * S):
        ref = np.tanh(ref @ Ws[c] + bs[c])
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)


def test_grad_parity_vs_sequential(pp_mesh):
    """jax.grad through the scan+ppermute schedule = the reverse pipeline;
    gradients must match the sequential oracle exactly (no bubble-mask
    leakage into active gradients)."""
    S, v, M = 4, 2, 4
    C = v * S
    rng = np.random.RandomState(3)
    Ws, bs = _mlp_chunks(rng, C)
    xs = jnp.asarray(rng.randn(M, 2, 8).astype(np.float32))

    def loss_pipe(W, b, x):
        out = fleet.pipeline_spmd(
            _body, {"W": W, "b": b}, x, mesh=pp_mesh, num_virtual_stages=v)
        return (out ** 2).mean()

    def loss_seq(Wf, bf, x):
        h = x
        for c in range(C):
            h = jnp.tanh(h @ Wf[c] + bf[c])
        return (h ** 2).mean()

    got = jax.grad(loss_pipe, argnums=(0, 1, 2))(
        jnp.asarray(Ws).reshape(v, S, 8, 8),
        jnp.asarray(bs).reshape(v, S, 8), xs)
    ref = jax.grad(loss_seq, argnums=(0, 1, 2))(
        jnp.asarray(Ws), jnp.asarray(bs), xs)
    np.testing.assert_allclose(np.asarray(got[0]).reshape(C, 8, 8),
                               np.asarray(ref[0]), atol=1e-5)
    np.testing.assert_allclose(np.asarray(got[1]).reshape(C, 8),
                               np.asarray(ref[1]), atol=1e-5)
    np.testing.assert_allclose(np.asarray(got[2]), np.asarray(ref[2]),
                               atol=1e-5)


def test_pytree_boundary_activations(pp_mesh):
    """Stage boundaries are pytrees — the reference's _p2p_helper
    handshakes arbitrary tensor tuples; here the tuple rides the same
    compiled ppermute (multi-stream models: residual + auxiliary)."""
    S, v, M = 4, 1, 4
    rng = np.random.RandomState(5)
    Ws, _ = _mlp_chunks(rng, S)
    params = {"W": jnp.asarray(Ws).reshape(v, S, 8, 8)}

    def body(p, xy):
        x, aux = xy
        x2 = jnp.tanh(x @ p["W"])
        return (x2, aux + x2.sum(-1))  # aux accumulates across stages

    xs = jnp.asarray(rng.randn(M, 2, 8).astype(np.float32))
    aux0 = jnp.zeros((M, 2), jnp.float32)
    out, aux = fleet.pipeline_spmd(body, params, (xs, aux0), mesh=pp_mesh,
                                   num_virtual_stages=v)
    ref, ra = np.asarray(xs), np.asarray(aux0)
    for c in range(S):
        ref = np.tanh(ref @ Ws[c])
        ra = ra + ref.sum(-1)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)
    np.testing.assert_allclose(np.asarray(aux), ra, atol=1e-4)


def test_schedule_stats_match_list_scheduler():
    """The compiled schedule's analytic accounting must agree with the
    measured bubble of the host-scheduled engine at the same geometry
    (S=4, v=2, M=4 -> 0.2727; v=1 -> (S-1)/(M+S-1))."""
    st = fleet.spmd_schedule_stats(4, 2, 4)
    assert abs(st["bubble_fraction"] - 0.2727) < 1e-4
    st1 = fleet.spmd_schedule_stats(4, 1, 4)
    assert abs(st1["bubble_fraction"] - 3 / 7) < 1e-3
    # deeper interleave shrinks the bubble monotonically
    bub = [fleet.spmd_schedule_stats(4, v, 8)["bubble_fraction"]
           for v in (1, 2, 4)]
    assert bub[0] > bub[1] > bub[2]


def test_layer_engine_trains(pp_mesh):
    rng = np.random.RandomState(0)
    pt.seed(0)

    def block():
        return nn.Sequential(nn.Linear(8, 8), nn.Tanh())

    pl = fleet.SpmdPipelineLayer(block, num_virtual_stages=2,
                                 loss_fn=nn.MSELoss())
    eng = fleet.SpmdPipelineParallel(pl, accumulate_steps=4)
    o = opt.AdamW(learning_rate=3e-3, parameters=eng.parameters())
    X = pt.to_tensor(rng.randn(8, 8).astype(np.float32))
    Y = pt.to_tensor(rng.randn(8, 8).astype(np.float32) * 0.1)
    losses = [float(eng.train_batch((X, Y), o).numpy()) for _ in range(20)]
    assert losses[-1] < losses[0] * 0.9, losses[::5]
    stats = eng.last_schedule_stats
    assert stats["bubble_fraction"] == 0.2727
    assert stats["n_chunks"] == 8


def test_layer_parity_vs_eager_sequential(pp_mesh):
    """The stacked-parameter pipeline Layer must produce the same outputs
    and parameter gradients as eagerly running its chunks in order."""
    rng = np.random.RandomState(1)
    pt.seed(7)

    def block():
        return nn.Sequential(nn.Linear(8, 8), nn.Tanh())

    pl = fleet.SpmdPipelineLayer(block, num_virtual_stages=2)
    S, v = pl.num_stages, pl.num_virtual_stages
    xs = pt.to_tensor(rng.randn(4, 2, 8).astype(np.float32),
                      stop_gradient=False)
    out = pl(xs)
    loss = (out * out).mean()
    loss.backward()

    # eager oracle: apply chunks c = r*S + s in order with the same weights
    W = pl._stacked()["0.weight"].numpy()  # [v, S, in, out]
    b = pl._stacked()["0.bias"].numpy()
    h = xs.numpy()
    for c in range(S * v):
        r, s = divmod(c, S)
        h = np.tanh(h @ W[r, s] + b[r, s])
    np.testing.assert_allclose(out.numpy(), h, atol=1e-5)
    gW = pl._stacked()["0.weight"].grad
    assert gW is not None and np.isfinite(gW.numpy()).all()
    assert np.abs(gW.numpy()).max() > 0


def test_train_step_integration(pp_mesh):
    """Whole-step SPMD compile: TrainStep shards the stacked parameters
    over pp via their _sharding_spec and the loss stays finite."""
    pt.seed(2)
    rng = np.random.RandomState(2)

    def block():
        return nn.Sequential(nn.Linear(8, 8), nn.Tanh())

    pl = fleet.SpmdPipelineLayer(block, num_virtual_stages=1)
    o = opt.AdamW(learning_rate=1e-3, parameters=pl.parameters())
    mse = nn.MSELoss()

    def loss_fn(m, x, y):
        out = m(x)
        return mse(pt.reshape(out, [-1, 8]), y)

    step = pt.jit.TrainStep(pl, loss_fn, o, mesh=pp_mesh)
    Xm = pt.to_tensor(rng.randn(4, 2, 8).astype(np.float32))
    Yf = pt.to_tensor(rng.randn(8, 8).astype(np.float32))
    v1 = float(step(Xm, Yf).numpy())
    v2 = float(step(Xm, Yf).numpy())
    assert np.isfinite(v1) and np.isfinite(v2) and v2 < v1


def test_stateless_block_required(pp_mesh):
    with pytest.raises(ValueError, match="stateless"):
        fleet.SpmdPipelineLayer(lambda: nn.BatchNorm1D(8))


def test_loss_parity_spmd_vs_host_scheduled(pp_mesh):
    """Both pipeline engines, same chunk weights, same batch -> same loss
    (the VERDICT 'unchanged loss parity' criterion for the new path)."""
    rng = np.random.RandomState(9)
    S, v = 4, 2
    pt.seed(11)

    def block():
        return nn.Sequential(nn.Linear(8, 8), nn.Tanh())

    pl = fleet.SpmdPipelineLayer(block, num_virtual_stages=v,
                                 loss_fn=nn.MSELoss())
    # host-scheduled engine over layers rebuilt with the SAME weights,
    # in chunk order c = r*S + s
    W = pl._stacked()["0.weight"].numpy()
    b = pl._stacked()["0.bias"].numpy()
    descs = []
    for c in range(S * v):
        r, s = divmod(c, S)
        lin = nn.Linear(8, 8)
        lin.weight.set_value(W[r, s])
        lin.bias.set_value(b[r, s])
        descs += [lin, nn.Tanh()]
    host = fleet.PipelineLayer(descs, num_stages=S,
                               num_virtual_pipeline_stages=v,
                               loss_fn=nn.MSELoss())
    hostp = fleet.PipelineParallel(host, accumulate_steps=4)
    spmd = fleet.SpmdPipelineParallel(pl, accumulate_steps=4)

    X = rng.randn(8, 8).astype(np.float32)
    Y = rng.randn(8, 8).astype(np.float32)
    o1 = opt.SGD(learning_rate=0.0, parameters=spmd.parameters())
    o2 = opt.SGD(learning_rate=0.0, parameters=hostp.parameters())
    l1 = float(spmd.train_batch((pt.to_tensor(X), pt.to_tensor(Y)),
                                o1).numpy())
    l2 = float(hostp.train_batch((pt.to_tensor(X), pt.to_tensor(Y)),
                                 o2).numpy())
    np.testing.assert_allclose(l1, l2, rtol=1e-5)


def test_dp_x_pp_combined_train_step(pp_mesh):
    """The real pod topology: batch sharded over dp AND stages over pp in
    ONE compiled TrainStep — GSPMD shards the micro-batch dim while the
    manual shard_map owns only the pp axis (axis_names={'pp'}), with loss
    parity against the replicated-batch run."""
    from paddle_tpu.distributed import P

    pt.seed(0)
    rng = np.random.RandomState(0)

    def block():
        return nn.Sequential(nn.Linear(8, 8), nn.Tanh())

    mse = nn.MSELoss()

    def loss_fn(m, x, y):
        out = m(x)
        return mse(pt.reshape(out, [-1, 8]), pt.reshape(y, [-1, 8]))

    Xm = pt.to_tensor(rng.randn(4, 4, 8).astype(np.float32))
    Ym = pt.to_tensor(rng.randn(4, 4, 8).astype(np.float32))

    pt.seed(3)
    pl = fleet.SpmdPipelineLayer(block, num_virtual_stages=2)
    o = opt.AdamW(learning_rate=1e-3, parameters=pl.parameters())
    sharded = pt.jit.TrainStep(pl, loss_fn, o, mesh=pp_mesh,
                               input_spec=P(None, "dp"))
    v1 = float(sharded(Xm, Ym).numpy())

    pt.seed(3)
    pl2 = fleet.SpmdPipelineLayer(block, num_virtual_stages=2)
    o2 = opt.AdamW(learning_rate=1e-3, parameters=pl2.parameters())
    repl = pt.jit.TrainStep(pl2, loss_fn, o2, mesh=pp_mesh,
                            input_spec=P())
    b1 = float(repl(Xm, Ym).numpy())
    assert abs(b1 - v1) < 5e-5 * max(1.0, abs(b1)), (b1, v1)
    # and the sharded step actually trains
    v2 = float(sharded(Xm, Ym).numpy())
    assert v2 < v1


# ===================== checkpoint conversion across layouts ==================
# VERDICT r4 item 6: train in one pipeline layout, convert the checkpoint,
# resume in the other — identical loss trajectory (reference Converter
# surface, auto_parallel/converter.py:25, extended to the pipeline case).
from paddle_tpu.distributed.auto_parallel.converter import (  # noqa: E402
    pipeline_state_to_spmd, spmd_state_to_pipeline)

_S, _V = 4, 2
_CHUNKS = _S * _V
_MICRO = 8


def _block_factory():
    return nn.Sequential(nn.Linear(8, 8), nn.Tanh())


def _conv_data(steps=4):
    rng = np.random.RandomState(42)
    return [(pt.to_tensor(rng.randn(_MICRO, 8).astype(np.float32)),
             pt.to_tensor(rng.randn(_MICRO, 8).astype(np.float32)))
            for _ in range(steps)]


def _spmd_engine(mesh, seed=21):
    pt.seed(seed)
    spl = fleet.SpmdPipelineLayer(_block_factory, num_virtual_stages=_V,
                                  mesh=mesh, loss_fn=nn.MSELoss())
    eng = fleet.SpmdPipelineParallel(spl, accumulate_steps=_MICRO)
    o = opt.SGD(learning_rate=0.1, parameters=eng.parameters())
    return spl, eng, o


def _host_engine(mesh, seed=22):
    pt.seed(seed)
    blocks = [_block_factory() for _ in range(_CHUNKS)]
    pl = fleet.PipelineLayer(blocks, num_stages=_S,
                             num_virtual_pipeline_stages=_V,
                             loss_fn=nn.MSELoss(), mesh=mesh)
    eng = fleet.PipelineParallel(pl, accumulate_steps=_MICRO)
    o = opt.SGD(learning_rate=0.1, parameters=eng.parameters())
    return pl, eng, o


def test_spmd_to_host_resume_identical_trajectory(pp_mesh):
    data = _conv_data(4)
    # full spmd run: 4 steps
    _, eng, o = _spmd_engine(pp_mesh)
    full = [float(eng.train_batch(d, o).numpy()) for d in data]
    # second spmd run: 2 steps, convert, resume 2 steps on the HOST engine
    spl2, eng2, o2 = _spmd_engine(pp_mesh)
    part = [float(eng2.train_batch(d, o2).numpy()) for d in data[:2]]
    np.testing.assert_allclose(part, full[:2], rtol=1e-6)
    host_state = spmd_state_to_pipeline(spl2.state_dict(), _S, _V,
                                        block_is_container=False)
    pl, heng, ho = _host_engine(pp_mesh)
    pl.set_state_dict(host_state)
    resumed = [float(heng.train_batch(d, ho).numpy()) for d in data[2:]]
    np.testing.assert_allclose(resumed, full[2:], rtol=5e-4)


def test_host_to_spmd_resume_identical_trajectory(pp_mesh):
    data = _conv_data(4)
    pl, heng, ho = _host_engine(pp_mesh, seed=23)
    full = [float(heng.train_batch(d, ho).numpy()) for d in data]
    pl2, heng2, ho2 = _host_engine(pp_mesh, seed=23)
    part = [float(heng2.train_batch(d, ho2).numpy()) for d in data[:2]]
    np.testing.assert_allclose(part, full[:2], rtol=1e-6)
    spmd_state = pipeline_state_to_spmd(pl2.state_dict(), _S, _V,
                                        block_is_container=False)
    spl, seng, so = _spmd_engine(pp_mesh, seed=24)
    spl.set_state_dict(spmd_state)
    resumed = [float(seng.train_batch(d, so).numpy()) for d in data[2:]]
    np.testing.assert_allclose(resumed, full[2:], rtol=5e-4)


def test_spmd_to_plain_model_serve(pp_mesh):
    """Pod-trained (spmd) checkpoint serves on a plain sequential model:
    the 'train on a pod, fine-tune/serve single-host' path."""
    spl, eng, o = _spmd_engine(pp_mesh, seed=25)
    data = _conv_data(1)
    eng.train_batch(data[0], o)
    plain_state = spmd_state_to_pipeline(
        spl.state_dict(), _S, _V, prefix="", block_is_container=False)
    pt.seed(26)
    plain = nn.Sequential(*[_block_factory() for _ in range(_CHUNKS)])
    plain.set_state_dict(plain_state)
    x = pt.to_tensor(np.random.RandomState(5)
                     .randn(4, 8).astype(np.float32))
    want = spl(pt.reshape(x, [4, 1, 8]))  # M=4 micro-batches of 1
    got = plain(x)
    np.testing.assert_allclose(
        got.numpy(), np.asarray(want.numpy()).reshape(4, 8), atol=1e-5)


def test_conversion_rejects_wrong_shapes(pp_mesh):
    spl, _, _ = _spmd_engine(pp_mesh, seed=27)
    state = spl.state_dict()
    with pytest.raises(ValueError, match="lead with"):
        spmd_state_to_pipeline(
            {k: np.zeros((3, 3)) for k in state}, _S, _V)
    with pytest.raises(ValueError, match="one trunk layer"):
        pipeline_state_to_spmd(
            {f"layers.{i}.0.weight": np.zeros((8, 8))
             for i in range(2 * _CHUNKS)}, _S, _V,
            block_is_container=False)


# ===================== heterogeneous + tied-weight stages ====================
# VERDICT r4 item 3: per-stage bodies (lax.switch over a padded stacked
# param superset) and tied weights (replicated shared params whose grads
# psum over pp — SharedLayerDesc semantics, pp_layers.py:77).

class _ConvBlock(nn.Layer):
    def __init__(self, F=8):
        super().__init__()
        self.conv = nn.Conv1D(F, F, 3, padding=1)

    def forward(self, x):                       # [B, T, F]
        h = pt.transpose(x, [0, 2, 1])
        h = nn.functional.relu(self.conv(h))
        return pt.transpose(h, [0, 2, 1])


class _RnnBlock(nn.Layer):
    def __init__(self, F=8):
        super().__init__()
        self.rnn = nn.SimpleRNN(F, F)

    def forward(self, x):
        out, _ = self.rnn(x)
        return out


class _HeadBlock(nn.Layer):
    def __init__(self, F=8):
        super().__init__()
        self.fc = nn.Linear(F, F)

    def forward(self, x):
        return self.fc(x)


def test_hetero_conv_rnn_head_trains_with_parity(pp_mesh):
    """conv -> conv -> rnn -> head, one body per stage, trained 2 steps:
    loss trajectory equals the eager sequential stack with tied initial
    weights."""
    pt.seed(31)
    hl = fleet.SpmdHeteroPipelineLayer(
        [_ConvBlock, _ConvBlock, _RnnBlock, _HeadBlock], mesh=pp_mesh)
    oracle = [_ConvBlock(), _ConvBlock(), _RnnBlock(), _HeadBlock()]
    for c, blk in enumerate(oracle):
        blk.set_state_dict({k: pt.to_tensor(v)
                            for k, v in hl.chunk_state_dict(c).items()})

    rng = np.random.RandomState(31)
    mse = nn.MSELoss()
    o_h = opt.SGD(learning_rate=0.05, parameters=hl.parameters())
    o_e = opt.SGD(learning_rate=0.05,
                  parameters=[p for b in oracle for p in b.parameters()])
    M, B, T, F = 4, 2, 6, 8
    for step in range(2):
        X = rng.randn(M, B, T, F).astype(np.float32)
        Y = rng.randn(M, B, T, F).astype(np.float32)
        out = hl(pt.to_tensor(X))
        loss_h = mse(out, pt.to_tensor(Y))
        loss_h.backward()
        o_h.step()
        o_h.clear_grad()

        h = pt.to_tensor(X.reshape(M * B, T, F))
        for blk in oracle:
            h = blk(h)
        loss_e = mse(h, pt.to_tensor(Y.reshape(M * B, T, F)))
        loss_e.backward()
        o_e.step()
        o_e.clear_grad()
        np.testing.assert_allclose(
            float(loss_h.numpy()), float(loss_e.numpy()), rtol=5e-4,
            err_msg=f"step {step}")


class _SharedUserBlock(nn.Layer):
    """Chunk that runs x through the TIED adapter then its own linear —
    forward takes (x, shared): the hetero engine hands it the shared
    sublayer."""

    def __init__(self, F=8):
        super().__init__()
        self.fc = nn.Linear(F, F)

    def forward(self, x, shared):
        return pt.tanh(self.fc(shared(x)))


class _PlainBlock(nn.Layer):
    def __init__(self, F=8):
        super().__init__()
        self.fc = nn.Linear(F, F)

    def forward(self, x):
        return pt.tanh(self.fc(x))


def test_tied_shared_layer_grads_sum_over_pp(pp_mesh):
    """A shared Linear consumed by chunks 0 AND 3 (both pipeline ends):
    its gradient equals the oracle's sum of both contributions — the
    psum-over-pp the reference implements with SharedLayerDesc's manual
    allreduce."""
    pt.seed(33)
    hl = fleet.SpmdHeteroPipelineLayer(
        [_SharedUserBlock, _PlainBlock, _PlainBlock, _SharedUserBlock],
        mesh=pp_mesh, shared_factory=lambda: nn.Linear(8, 8))
    blocks = [_SharedUserBlock(), _PlainBlock(), _PlainBlock(),
              _SharedUserBlock()]
    for c, blk in enumerate(blocks):
        blk.set_state_dict({k: pt.to_tensor(v)
                            for k, v in hl.chunk_state_dict(c).items()})
    shared_oracle = nn.Linear(8, 8)
    shared_oracle.set_state_dict(
        {k: pt.to_tensor(v.numpy()) for k, v in
         dict(hl.shared.named_parameters()).items()})

    rng = np.random.RandomState(33)
    M, B, F = 4, 2, 8
    X = rng.randn(M, B, F).astype(np.float32)
    out = hl(pt.to_tensor(X))
    loss = (out * out).mean()
    loss.backward()

    h = pt.to_tensor(X.reshape(M * B, F))
    for blk in blocks:
        if isinstance(blk, _SharedUserBlock):
            h = blk(h, shared_oracle)
        else:
            h = blk(h)
    loss_e = (h * h).mean()
    loss_e.backward()
    np.testing.assert_allclose(float(loss.numpy()), float(loss_e.numpy()),
                               rtol=5e-4)
    np.testing.assert_allclose(
        hl.shared.weight.grad.numpy(), shared_oracle.weight.grad.numpy(),
        atol=1e-5)


def test_tied_embedding_lm_trains_with_parity(pp_mesh):
    """Embedding-tied LM: shared embedding feeds the pipeline AND
    projects the logits; grads from both uses sum. Trained 2 steps with
    loss parity vs the single-process sequential oracle."""
    V, d = 32, 8

    class TiedLM(nn.Layer):
        def __init__(self, trunk):
            super().__init__()
            self.embed = nn.Embedding(V, d)
            self.trunk = trunk

        def forward(self, ids):                 # [M, B, T]
            h = self.embed(ids)
            h = self.trunk(h)
            return pt.matmul(h, pt.transpose(self.embed.weight, [1, 0]))

    def trunk_factory():
        return nn.Sequential(nn.Linear(d, d), nn.Tanh())

    pt.seed(35)
    spl = fleet.SpmdPipelineLayer(trunk_factory, mesh=pp_mesh)
    lm = TiedLM(spl)

    pt.seed(36)
    ce = nn.CrossEntropyLoss()
    plain_blocks = [trunk_factory() for _ in range(spl.num_chunks)]
    W = spl._stacked()["0.weight"].numpy().reshape(-1, d, d)
    bvec = spl._stacked()["0.bias"].numpy().reshape(-1, d)
    for c, blk in enumerate(plain_blocks):
        blk.set_state_dict({"0.weight": pt.to_tensor(W[c]),
                            "0.bias": pt.to_tensor(bvec[c])})

    class PlainLM(nn.Layer):
        def __init__(self):
            super().__init__()
            self.embed = nn.Embedding(V, d)
            self.blocks = nn.LayerList(plain_blocks)

        def forward(self, ids):                 # [N, T]
            h = self.embed(ids)
            for b in self.blocks:
                h = b(h)
            return pt.matmul(h, pt.transpose(self.embed.weight, [1, 0]))

    plain = PlainLM()
    plain.embed.set_state_dict(
        {"weight": pt.to_tensor(lm.embed.weight.numpy())})

    rng = np.random.RandomState(35)
    o1 = opt.SGD(learning_rate=0.1, parameters=lm.parameters())
    o2 = opt.SGD(learning_rate=0.1, parameters=plain.parameters())
    M, B, T = 4, 2, 5
    for step in range(2):
        ids = rng.randint(0, V, (M, B, T)).astype(np.int64)
        tgt = rng.randint(0, V, (M * B * T,)).astype(np.int64)
        logits = lm(pt.to_tensor(ids))
        l1 = ce(pt.reshape(logits, [-1, V]), pt.to_tensor(tgt))
        l1.backward()
        o1.step()
        o1.clear_grad()
        logits2 = plain(pt.to_tensor(ids.reshape(M * B, T)))
        l2 = ce(pt.reshape(logits2, [-1, V]), pt.to_tensor(tgt))
        l2.backward()
        o2.step()
        o2.clear_grad()
        np.testing.assert_allclose(float(l1.numpy()), float(l2.numpy()),
                                   rtol=5e-4, err_msg=f"step {step}")


def test_optional_kwarg_block_does_not_receive_shared(pp_mesh):
    """forward(self, x, mask=None) must NOT be handed the shared layer
    (review regression: parameter counting vs required-positional)."""
    calls = []

    class OptionalKw(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(8, 8)

        def forward(self, x, mask=None):
            calls.append(mask)
            return pt.tanh(self.fc(x))

    pt.seed(41)
    hl = fleet.SpmdHeteroPipelineLayer(
        [OptionalKw, OptionalKw, OptionalKw, OptionalKw], mesh=pp_mesh,
        shared_factory=lambda: nn.Linear(8, 8))
    rng = np.random.RandomState(41)
    out = hl(pt.to_tensor(rng.randn(4, 2, 8).astype(np.float32)))
    assert np.isfinite(out.numpy()).all()
    assert all(m is None for m in calls)


def test_conversion_tolerates_paramless_layers(pp_mesh):
    """A trunk with parameter-less layers (ReLU) between linears converts
    with index holes treated as empty slots (review regression)."""
    from paddle_tpu.distributed.auto_parallel.converter import (
        pipeline_state_to_spmd)
    # 8 trunk layers: Linear at even indices, ReLU (no params) at odd
    state = {f"{i}.weight": np.full((4, 4), i, np.float32)
             for i in range(0, 8, 2)}
    state.update({f"{i}.bias": np.full((4,), i, np.float32)
                  for i in range(0, 8, 2)})
    spmd = pipeline_state_to_spmd(state, 4, 1, prefix="")
    # chunk c covers layers [2c, 2c+2): child 0 = Linear, child 1 = ReLU
    assert spmd["0__weight"].shape == (1, 4, 4, 4)
    assert spmd["0__weight"][0, 2, 0, 0] == 4.0
