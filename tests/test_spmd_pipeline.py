"""SPMD (collective) pipeline: the compiled-ppermute engine that replaces
single-controller device_put hops so pipeline stages can span hosts
(reference counterpart: fleet/meta_parallel/pp_utils/p2p_communication.py
send_v2/recv_v2 + pipeline_parallel.py 1F1B/interleave)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.distributed as dist
import paddle_tpu.distributed.fleet as fleet
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt


@pytest.fixture()
def pp_mesh():
    return dist.init_mesh({"dp": 2, "pp": 4})


def _mlp_chunks(rng, C, d=8):
    Ws = rng.randn(C, d, d).astype(np.float32) * 0.3
    bs = rng.randn(C, d).astype(np.float32) * 0.1
    return Ws, bs


def _body(p, x):
    return jnp.tanh(x @ p["W"] + p["b"])


@pytest.mark.parametrize("v,M", [(1, 4), (2, 4), (1, 6), (2, 6), (3, 8)])
def test_forward_parity_vs_sequential(pp_mesh, v, M):
    """Any micro-count (no M % S constraint), any virtual-stage depth:
    the pipelined result equals running the chunks sequentially."""
    S = 4
    rng = np.random.RandomState(v * 10 + M)
    Ws, bs = _mlp_chunks(rng, v * S)
    params = {"W": jnp.asarray(Ws).reshape(v, S, 8, 8),
              "b": jnp.asarray(bs).reshape(v, S, 8)}
    xs = jnp.asarray(rng.randn(M, 2, 8).astype(np.float32))
    out = fleet.pipeline_spmd(_body, params, xs, mesh=pp_mesh,
                              num_virtual_stages=v)
    ref = np.asarray(xs)
    for c in range(v * S):
        ref = np.tanh(ref @ Ws[c] + bs[c])
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)


def test_grad_parity_vs_sequential(pp_mesh):
    """jax.grad through the scan+ppermute schedule = the reverse pipeline;
    gradients must match the sequential oracle exactly (no bubble-mask
    leakage into active gradients)."""
    S, v, M = 4, 2, 4
    C = v * S
    rng = np.random.RandomState(3)
    Ws, bs = _mlp_chunks(rng, C)
    xs = jnp.asarray(rng.randn(M, 2, 8).astype(np.float32))

    def loss_pipe(W, b, x):
        out = fleet.pipeline_spmd(
            _body, {"W": W, "b": b}, x, mesh=pp_mesh, num_virtual_stages=v)
        return (out ** 2).mean()

    def loss_seq(Wf, bf, x):
        h = x
        for c in range(C):
            h = jnp.tanh(h @ Wf[c] + bf[c])
        return (h ** 2).mean()

    got = jax.grad(loss_pipe, argnums=(0, 1, 2))(
        jnp.asarray(Ws).reshape(v, S, 8, 8),
        jnp.asarray(bs).reshape(v, S, 8), xs)
    ref = jax.grad(loss_seq, argnums=(0, 1, 2))(
        jnp.asarray(Ws), jnp.asarray(bs), xs)
    np.testing.assert_allclose(np.asarray(got[0]).reshape(C, 8, 8),
                               np.asarray(ref[0]), atol=1e-5)
    np.testing.assert_allclose(np.asarray(got[1]).reshape(C, 8),
                               np.asarray(ref[1]), atol=1e-5)
    np.testing.assert_allclose(np.asarray(got[2]), np.asarray(ref[2]),
                               atol=1e-5)


def test_pytree_boundary_activations(pp_mesh):
    """Stage boundaries are pytrees — the reference's _p2p_helper
    handshakes arbitrary tensor tuples; here the tuple rides the same
    compiled ppermute (multi-stream models: residual + auxiliary)."""
    S, v, M = 4, 1, 4
    rng = np.random.RandomState(5)
    Ws, _ = _mlp_chunks(rng, S)
    params = {"W": jnp.asarray(Ws).reshape(v, S, 8, 8)}

    def body(p, xy):
        x, aux = xy
        x2 = jnp.tanh(x @ p["W"])
        return (x2, aux + x2.sum(-1))  # aux accumulates across stages

    xs = jnp.asarray(rng.randn(M, 2, 8).astype(np.float32))
    aux0 = jnp.zeros((M, 2), jnp.float32)
    out, aux = fleet.pipeline_spmd(body, params, (xs, aux0), mesh=pp_mesh,
                                   num_virtual_stages=v)
    ref, ra = np.asarray(xs), np.asarray(aux0)
    for c in range(S):
        ref = np.tanh(ref @ Ws[c])
        ra = ra + ref.sum(-1)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)
    np.testing.assert_allclose(np.asarray(aux), ra, atol=1e-4)


def test_schedule_stats_match_list_scheduler():
    """The compiled schedule's analytic accounting must agree with the
    measured bubble of the host-scheduled engine at the same geometry
    (S=4, v=2, M=4 -> 0.2727; v=1 -> (S-1)/(M+S-1))."""
    st = fleet.spmd_schedule_stats(4, 2, 4)
    assert abs(st["bubble_fraction"] - 0.2727) < 1e-4
    st1 = fleet.spmd_schedule_stats(4, 1, 4)
    assert abs(st1["bubble_fraction"] - 3 / 7) < 1e-3
    # deeper interleave shrinks the bubble monotonically
    bub = [fleet.spmd_schedule_stats(4, v, 8)["bubble_fraction"]
           for v in (1, 2, 4)]
    assert bub[0] > bub[1] > bub[2]


def test_layer_engine_trains(pp_mesh):
    rng = np.random.RandomState(0)
    pt.seed(0)

    def block():
        return nn.Sequential(nn.Linear(8, 8), nn.Tanh())

    pl = fleet.SpmdPipelineLayer(block, num_virtual_stages=2,
                                 loss_fn=nn.MSELoss())
    eng = fleet.SpmdPipelineParallel(pl, accumulate_steps=4)
    o = opt.AdamW(learning_rate=3e-3, parameters=eng.parameters())
    X = pt.to_tensor(rng.randn(8, 8).astype(np.float32))
    Y = pt.to_tensor(rng.randn(8, 8).astype(np.float32) * 0.1)
    losses = [float(eng.train_batch((X, Y), o).numpy()) for _ in range(20)]
    assert losses[-1] < losses[0] * 0.9, losses[::5]
    stats = eng.last_schedule_stats
    assert stats["bubble_fraction"] == 0.2727
    assert stats["n_chunks"] == 8


def test_layer_parity_vs_eager_sequential(pp_mesh):
    """The stacked-parameter pipeline Layer must produce the same outputs
    and parameter gradients as eagerly running its chunks in order."""
    rng = np.random.RandomState(1)
    pt.seed(7)

    def block():
        return nn.Sequential(nn.Linear(8, 8), nn.Tanh())

    pl = fleet.SpmdPipelineLayer(block, num_virtual_stages=2)
    S, v = pl.num_stages, pl.num_virtual_stages
    xs = pt.to_tensor(rng.randn(4, 2, 8).astype(np.float32),
                      stop_gradient=False)
    out = pl(xs)
    loss = (out * out).mean()
    loss.backward()

    # eager oracle: apply chunks c = r*S + s in order with the same weights
    W = pl._stacked()["0.weight"].numpy()  # [v, S, in, out]
    b = pl._stacked()["0.bias"].numpy()
    h = xs.numpy()
    for c in range(S * v):
        r, s = divmod(c, S)
        h = np.tanh(h @ W[r, s] + b[r, s])
    np.testing.assert_allclose(out.numpy(), h, atol=1e-5)
    gW = pl._stacked()["0.weight"].grad
    assert gW is not None and np.isfinite(gW.numpy()).all()
    assert np.abs(gW.numpy()).max() > 0


def test_train_step_integration(pp_mesh):
    """Whole-step SPMD compile: TrainStep shards the stacked parameters
    over pp via their _sharding_spec and the loss stays finite."""
    pt.seed(2)
    rng = np.random.RandomState(2)

    def block():
        return nn.Sequential(nn.Linear(8, 8), nn.Tanh())

    pl = fleet.SpmdPipelineLayer(block, num_virtual_stages=1)
    o = opt.AdamW(learning_rate=1e-3, parameters=pl.parameters())
    mse = nn.MSELoss()

    def loss_fn(m, x, y):
        out = m(x)
        return mse(pt.reshape(out, [-1, 8]), y)

    step = pt.jit.TrainStep(pl, loss_fn, o, mesh=pp_mesh)
    Xm = pt.to_tensor(rng.randn(4, 2, 8).astype(np.float32))
    Yf = pt.to_tensor(rng.randn(8, 8).astype(np.float32))
    v1 = float(step(Xm, Yf).numpy())
    v2 = float(step(Xm, Yf).numpy())
    assert np.isfinite(v1) and np.isfinite(v2) and v2 < v1


def test_stateless_block_required(pp_mesh):
    with pytest.raises(ValueError, match="stateless"):
        fleet.SpmdPipelineLayer(lambda: nn.BatchNorm1D(8))


def test_loss_parity_spmd_vs_host_scheduled(pp_mesh):
    """Both pipeline engines, same chunk weights, same batch -> same loss
    (the VERDICT 'unchanged loss parity' criterion for the new path)."""
    rng = np.random.RandomState(9)
    S, v = 4, 2
    pt.seed(11)

    def block():
        return nn.Sequential(nn.Linear(8, 8), nn.Tanh())

    pl = fleet.SpmdPipelineLayer(block, num_virtual_stages=v,
                                 loss_fn=nn.MSELoss())
    # host-scheduled engine over layers rebuilt with the SAME weights,
    # in chunk order c = r*S + s
    W = pl._stacked()["0.weight"].numpy()
    b = pl._stacked()["0.bias"].numpy()
    descs = []
    for c in range(S * v):
        r, s = divmod(c, S)
        lin = nn.Linear(8, 8)
        lin.weight.set_value(W[r, s])
        lin.bias.set_value(b[r, s])
        descs += [lin, nn.Tanh()]
    host = fleet.PipelineLayer(descs, num_stages=S,
                               num_virtual_pipeline_stages=v,
                               loss_fn=nn.MSELoss())
    hostp = fleet.PipelineParallel(host, accumulate_steps=4)
    spmd = fleet.SpmdPipelineParallel(pl, accumulate_steps=4)

    X = rng.randn(8, 8).astype(np.float32)
    Y = rng.randn(8, 8).astype(np.float32)
    o1 = opt.SGD(learning_rate=0.0, parameters=spmd.parameters())
    o2 = opt.SGD(learning_rate=0.0, parameters=hostp.parameters())
    l1 = float(spmd.train_batch((pt.to_tensor(X), pt.to_tensor(Y)),
                                o1).numpy())
    l2 = float(hostp.train_batch((pt.to_tensor(X), pt.to_tensor(Y)),
                                 o2).numpy())
    np.testing.assert_allclose(l1, l2, rtol=1e-5)


def test_dp_x_pp_combined_train_step(pp_mesh):
    """The real pod topology: batch sharded over dp AND stages over pp in
    ONE compiled TrainStep — GSPMD shards the micro-batch dim while the
    manual shard_map owns only the pp axis (axis_names={'pp'}), with loss
    parity against the replicated-batch run."""
    from paddle_tpu.distributed import P

    pt.seed(0)
    rng = np.random.RandomState(0)

    def block():
        return nn.Sequential(nn.Linear(8, 8), nn.Tanh())

    mse = nn.MSELoss()

    def loss_fn(m, x, y):
        out = m(x)
        return mse(pt.reshape(out, [-1, 8]), pt.reshape(y, [-1, 8]))

    Xm = pt.to_tensor(rng.randn(4, 4, 8).astype(np.float32))
    Ym = pt.to_tensor(rng.randn(4, 4, 8).astype(np.float32))

    pt.seed(3)
    pl = fleet.SpmdPipelineLayer(block, num_virtual_stages=2)
    o = opt.AdamW(learning_rate=1e-3, parameters=pl.parameters())
    sharded = pt.jit.TrainStep(pl, loss_fn, o, mesh=pp_mesh,
                               input_spec=P(None, "dp"))
    v1 = float(sharded(Xm, Ym).numpy())

    pt.seed(3)
    pl2 = fleet.SpmdPipelineLayer(block, num_virtual_stages=2)
    o2 = opt.AdamW(learning_rate=1e-3, parameters=pl2.parameters())
    repl = pt.jit.TrainStep(pl2, loss_fn, o2, mesh=pp_mesh,
                            input_spec=P())
    b1 = float(repl(Xm, Ym).numpy())
    assert abs(b1 - v1) < 5e-5 * max(1.0, abs(b1)), (b1, v1)
    # and the sharded step actually trains
    v2 = float(sharded(Xm, Ym).numpy())
    assert v2 < v1
