"""Op correctness against numpy oracles (OpTest style, SURVEY.md §4)."""
import numpy as np
import pytest

import paddle_tpu as pt


def rnd(*shape, dtype=np.float32):
    return np.random.rand(*shape).astype(dtype)


class TestMath:
    def test_binary_broadcast(self):
        a, b = rnd(3, 1, 4), rnd(2, 1)
        np.testing.assert_allclose(
            pt.add(pt.to_tensor(a), pt.to_tensor(b)).numpy(), a + b, rtol=1e-6)

    def test_scale(self):
        x = rnd(3)
        np.testing.assert_allclose(
            pt.scale(pt.to_tensor(x), 2.0, 1.0).numpy(), x * 2 + 1, rtol=1e-6)
        np.testing.assert_allclose(
            pt.scale(pt.to_tensor(x), 2.0, 1.0, bias_after_scale=False).numpy(),
            (x + 1) * 2, rtol=1e-6)

    def test_clip(self):
        x = np.array([-2.0, 0.5, 3.0], np.float32)
        np.testing.assert_allclose(
            pt.clip(pt.to_tensor(x), 0.0, 1.0).numpy(), [0, 0.5, 1])

    def test_cumsum(self):
        x = rnd(2, 3)
        np.testing.assert_allclose(pt.cumsum(pt.to_tensor(x), 1).numpy(),
                                   np.cumsum(x, 1), rtol=1e-6)
        np.testing.assert_allclose(pt.cumsum(pt.to_tensor(x)).numpy(),
                                   np.cumsum(x), rtol=1e-6)

    def test_remainder_floordiv(self):
        a = np.array([7.0, -7.0], np.float32)
        b = np.array([3.0, 3.0], np.float32)
        np.testing.assert_allclose(
            pt.remainder(pt.to_tensor(a), pt.to_tensor(b)).numpy(),
            np.remainder(a, b))
        np.testing.assert_allclose(
            pt.floor_divide(pt.to_tensor(a), pt.to_tensor(b)).numpy(),
            np.floor_divide(a, b))


class TestReduction:
    def test_sum_axes(self):
        x = rnd(2, 3, 4)
        t = pt.to_tensor(x)
        np.testing.assert_allclose(pt.sum(t).numpy(), x.sum(), rtol=1e-5)
        np.testing.assert_allclose(pt.sum(t, axis=1).numpy(), x.sum(1),
                                   rtol=1e-5)
        np.testing.assert_allclose(
            pt.sum(t, axis=[0, 2], keepdim=True).numpy(),
            x.sum((0, 2), keepdims=True), rtol=1e-5)

    def test_mean_std_var(self):
        x = rnd(4, 5)
        t = pt.to_tensor(x)
        np.testing.assert_allclose(pt.mean(t, 0).numpy(), x.mean(0), rtol=1e-5)
        np.testing.assert_allclose(pt.std(t).numpy(), x.std(ddof=1), rtol=1e-5)
        np.testing.assert_allclose(pt.var(t, unbiased=False).numpy(),
                                   x.var(), rtol=1e-5)

    def test_argmax_argmin(self):
        x = rnd(3, 5)
        t = pt.to_tensor(x)
        np.testing.assert_array_equal(pt.argmax(t, 1).numpy(), x.argmax(1))
        np.testing.assert_array_equal(pt.argmin(t, 0).numpy(), x.argmin(0))
        # x64 is disabled on TPU: "int64" results are stored 32-bit
        assert pt.argmax(t, 1).dtype in (pt.int64, pt.int32)

    def test_topk(self):
        x = rnd(2, 8)
        v, i = pt.topk(pt.to_tensor(x), 3)
        expect = np.sort(x, 1)[:, ::-1][:, :3]
        np.testing.assert_allclose(v.numpy(), expect, rtol=1e-6)
        v2, _ = pt.topk(pt.to_tensor(x), 3, largest=False)
        np.testing.assert_allclose(v2.numpy(), np.sort(x, 1)[:, :3], rtol=1e-6)

    def test_logsumexp(self):
        x = rnd(3, 4)
        np.testing.assert_allclose(
            pt.logsumexp(pt.to_tensor(x), 1).numpy(),
            np.log(np.exp(x).sum(1)), rtol=1e-4)

    def test_all_any(self):
        x = np.array([[True, False], [True, True]])
        t = pt.to_tensor(x)
        np.testing.assert_array_equal(pt.ops.OPS["all"](t, axis=1).numpy(),
                                      x.all(1))
        np.testing.assert_array_equal(pt.ops.OPS["any"](t, axis=0).numpy(),
                                      x.any(0))


class TestManipulation:
    def test_reshape_transpose(self):
        x = rnd(2, 3, 4)
        t = pt.to_tensor(x)
        assert pt.reshape(t, [4, 6]).shape == [4, 6]
        np.testing.assert_allclose(
            pt.transpose(t, [2, 0, 1]).numpy(), x.transpose(2, 0, 1))

    def test_concat_stack_split(self):
        a, b = rnd(2, 3), rnd(2, 3)
        np.testing.assert_allclose(
            pt.concat([pt.to_tensor(a), pt.to_tensor(b)], 1).numpy(),
            np.concatenate([a, b], 1))
        np.testing.assert_allclose(
            pt.stack([pt.to_tensor(a), pt.to_tensor(b)], 0).numpy(),
            np.stack([a, b]))
        parts = pt.split(pt.to_tensor(rnd(6, 2)), 3)
        assert len(parts) == 3 and parts[0].shape == [2, 2]
        parts = pt.split(pt.to_tensor(rnd(7, 2)), [2, -1, 3])
        assert [p.shape[0] for p in parts] == [2, 2, 3]

    def test_squeeze_unsqueeze_flatten(self):
        x = rnd(1, 3, 1, 4)
        t = pt.to_tensor(x)
        assert pt.squeeze(t).shape == [3, 4]
        assert pt.squeeze(t, 0).shape == [3, 1, 4]
        assert pt.unsqueeze(pt.to_tensor(rnd(3)), [0, 2]).shape == [1, 3, 1]
        assert pt.flatten(pt.to_tensor(rnd(2, 3, 4)), 1, 2).shape == [2, 12]

    def test_gather_scatter(self):
        x = rnd(5, 3)
        idx = np.array([0, 3])
        np.testing.assert_allclose(
            pt.gather(pt.to_tensor(x), pt.to_tensor(idx)).numpy(), x[idx])
        upd = rnd(2, 3)
        out = pt.scatter(pt.to_tensor(x), pt.to_tensor(idx),
                         pt.to_tensor(upd)).numpy()
        expect = x.copy(); expect[idx] = upd
        np.testing.assert_allclose(out, expect)

    def test_gather_nd(self):
        x = rnd(3, 4, 5)
        idx = np.array([[0, 1], [2, 3]])
        np.testing.assert_allclose(
            pt.gather_nd(pt.to_tensor(x), pt.to_tensor(idx)).numpy(),
            x[[0, 2], [1, 3]])

    def test_where_nonzero(self):
        c = np.array([[True, False], [False, True]])
        a, b = rnd(2, 2), rnd(2, 2)
        np.testing.assert_allclose(
            pt.where(pt.to_tensor(c), pt.to_tensor(a), pt.to_tensor(b)).numpy(),
            np.where(c, a, b))
        nz = pt.nonzero(pt.to_tensor(c)).numpy()
        np.testing.assert_array_equal(nz, np.stack(np.nonzero(c), -1))

    def test_pad(self):
        x = rnd(2, 3)
        out = pt.ops.OPS["pad"](pt.to_tensor(x), [1, 1, 2, 2]).numpy()
        assert out.shape == (4, 7)

    def test_tril_triu(self):
        x = rnd(4, 4)
        np.testing.assert_allclose(pt.tril(pt.to_tensor(x)).numpy(),
                                   np.tril(x))
        np.testing.assert_allclose(pt.triu(pt.to_tensor(x), 1).numpy(),
                                   np.triu(x, 1))

    def test_tile_expand(self):
        x = rnd(1, 3)
        assert pt.tile(pt.to_tensor(x), [2, 2]).shape == [2, 6]
        assert pt.expand(pt.to_tensor(x), [4, 3]).shape == [4, 3]
        assert pt.expand(pt.to_tensor(x), [4, -1]).shape == [4, 3]

    def test_sort_argsort(self):
        x = rnd(3, 5)
        np.testing.assert_allclose(pt.sort(pt.to_tensor(x), 1).numpy(),
                                   np.sort(x, 1))
        np.testing.assert_array_equal(pt.argsort(pt.to_tensor(x), 1).numpy(),
                                      np.argsort(x, 1))

    def test_one_hot(self):
        x = np.array([0, 2, 1])
        oh = pt.one_hot(pt.to_tensor(x), 3).numpy()
        np.testing.assert_allclose(oh, np.eye(3)[x])

    def test_take_put_along_axis(self):
        x = rnd(3, 4)
        idx = np.array([[1], [0], [2]])
        np.testing.assert_allclose(
            pt.take_along_axis(pt.to_tensor(x), pt.to_tensor(idx), 1,
                               broadcast=False).numpy(),
            np.take_along_axis(x, idx, 1))
        out = pt.put_along_axis(pt.to_tensor(x), pt.to_tensor(idx),
                                9.0, 1).numpy()
        expect = x.copy()
        np.put_along_axis(expect, idx, 9.0, 1)
        np.testing.assert_allclose(out, expect)

    def test_flip_roll(self):
        x = rnd(3, 4)
        np.testing.assert_allclose(pt.flip(pt.to_tensor(x), 0).numpy(),
                                   x[::-1])
        np.testing.assert_allclose(pt.roll(pt.to_tensor(x), 1, 1).numpy(),
                                   np.roll(x, 1, 1))


class TestLinalg:
    def test_matmul_transpose_flags(self):
        a, b = rnd(3, 4), rnd(5, 4)
        np.testing.assert_allclose(
            pt.matmul(pt.to_tensor(a), pt.to_tensor(b),
                      transpose_y=True).numpy(), a @ b.T, rtol=1e-5)
        np.testing.assert_allclose(
            pt.matmul(pt.to_tensor(a.T), pt.to_tensor(b.T),
                      transpose_x=True).numpy(), a @ b.T, rtol=1e-5)

    def test_bmm(self):
        a, b = rnd(2, 3, 4), rnd(2, 4, 5)
        np.testing.assert_allclose(pt.bmm(pt.to_tensor(a),
                                          pt.to_tensor(b)).numpy(),
                                   a @ b, rtol=1e-5)

    def test_einsum(self):
        a, b = rnd(3, 4), rnd(4, 5)
        np.testing.assert_allclose(
            pt.einsum("ij,jk->ik", pt.to_tensor(a), pt.to_tensor(b)).numpy(),
            a @ b, rtol=1e-5)

    def test_norm(self):
        x = rnd(3, 4)
        np.testing.assert_allclose(pt.norm(pt.to_tensor(x)).numpy(),
                                   np.linalg.norm(x), rtol=1e-5)
        np.testing.assert_allclose(pt.norm(pt.to_tensor(x), p=1, axis=1).numpy(),
                                   np.abs(x).sum(1), rtol=1e-5)

    def test_solve_inverse_det(self):
        a = rnd(3, 3) + np.eye(3, dtype=np.float32) * 3
        b = rnd(3, 2)
        np.testing.assert_allclose(
            pt.solve(pt.to_tensor(a), pt.to_tensor(b)).numpy(),
            np.linalg.solve(a, b), rtol=1e-4)
        np.testing.assert_allclose(pt.inverse(pt.to_tensor(a)).numpy(),
                                   np.linalg.inv(a), rtol=1e-4)
        np.testing.assert_allclose(pt.det(pt.to_tensor(a)).numpy(),
                                   np.linalg.det(a), rtol=1e-4)

    def test_svd_qr_cholesky(self):
        a = rnd(4, 3)
        u, s, vt = pt.svd(pt.to_tensor(a))
        np.testing.assert_allclose(
            (u.numpy() * s.numpy()) @ vt.numpy(), a, rtol=1e-4, atol=1e-5)
        q, r = pt.qr(pt.to_tensor(a))
        np.testing.assert_allclose(q.numpy() @ r.numpy(), a, rtol=1e-4,
                                   atol=1e-5)
        spd = a.T @ a + np.eye(3, dtype=np.float32)
        L = pt.cholesky(pt.to_tensor(spd))
        np.testing.assert_allclose(L.numpy() @ L.numpy().T, spd, rtol=1e-4)
