"""Resilient trainer worker for the chaos/preemption integration tests
(run via the elastic launcher or directly — NOT a pytest file).

A tiny deterministic fit wrapped in FitResilience. Env contract:

* ``RESILIENCE_TEST_DIR`` — run directory (checkpoints + progress files).
* ``RESILIENCE_TEST_STEPS`` — target global step count (default 8).
* ``RESILIENCE_TEST_SELF_PREEMPT_STEP`` — request preemption at this
  step on a FRESH (non-resumed) run: graceful stop, final commit, exit
  with the resumable code. A resumed run ignores it (so the launcher's
  relaunch completes the job).
* ``RESILIENCE_TEST_STEP_SLEEP`` — seconds of sleep per step (gives the
  parent time to deliver a real SIGTERM).
* ``RESILIENCE_TEST_SAVE_EVERY`` — periodic step-checkpoint cadence
  ("" disables: the only possible commit is the preemption save).
* ``PADDLE_TPU_CHAOS_*`` — the chaos harness (kill-at-step etc.).

Progress: appends ``{"gs", "pid", "t"}`` lines to ``steps.jsonl``. On
resume, writes ``resume_<pid>.json`` with the restored step and a sha256
digest of the restored parameters (the test recomputes the digest from
the checkpoint itself to prove the restore was bit-identical). On
reaching the target, writes ``done.json``.
"""
import hashlib
import json
import os
import sys
import time

import numpy as np


def state_digest(named_arrays) -> str:
    """sha256 over raw bytes of (name, array) in name order — the
    bit-identical oracle shared with tests/test_resilience.py."""
    h = hashlib.sha256()
    for name in sorted(named_arrays):
        h.update(name.encode())
        h.update(np.ascontiguousarray(np.asarray(named_arrays[name])).tobytes())
    return h.hexdigest()


def main():
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    from paddle_tpu.resilience import FitResilience

    run_dir = os.environ["RESILIENCE_TEST_DIR"]
    target = int(os.environ.get("RESILIENCE_TEST_STEPS", "8"))
    self_preempt = os.environ.get("RESILIENCE_TEST_SELF_PREEMPT_STEP")
    step_sleep = float(os.environ.get("RESILIENCE_TEST_STEP_SLEEP", "0"))
    save_every = os.environ.get("RESILIENCE_TEST_SAVE_EVERY", "1")
    steps_path = os.path.join(run_dir, "steps.jsonl")

    pt.seed(7)
    model = pt.hapi.Model(nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                                        nn.Linear(16, 1)))
    model.prepare(pt.optimizer.SGD(learning_rate=0.05,
                                   parameters=model.parameters()),
                  nn.MSELoss())
    fr = FitResilience(
        checkpoint_dir=os.path.join(run_dir, "ckpt"),
        save_every_steps=int(save_every) if save_every else None,
        keep_last_k=None, preemption=True)
    resumed = fr.restore(model)
    if resumed is not None:
        sd = {k: v.numpy() for k, v in model.network.state_dict().items()}
        with open(os.path.join(run_dir, f"resume_{os.getpid()}.json"),
                  "w") as f:
            json.dump({"resumed_from": resumed,
                       "digest": state_digest(sd)}, f)

    class Progress(pt.callbacks.Callback):
        def on_train_batch_end(self, step, logs=None):
            with open(steps_path, "a") as f:
                f.write(json.dumps({"gs": fr.global_step,
                                    "pid": os.getpid(),
                                    "t": time.time()}) + "\n")
            if step_sleep:
                time.sleep(step_sleep)
            if self_preempt is not None and resumed is None and \
                    fr.global_step == int(self_preempt):
                fr.listener.request("self_test")

    remaining = target - (resumed or 0)
    if remaining > 0:
        rng = np.random.RandomState(0)
        data = [(rng.randn(4, 8).astype(np.float32),
                 rng.randn(4, 1).astype(np.float32)) for _ in range(4)]
        model.fit(data, epochs=(remaining + len(data) - 1) // len(data),
                  num_iters=remaining, verbose=0,
                  callbacks=[fr, Progress()])
    if not fr.preempted:
        with open(os.path.join(run_dir, "done.json"), "w") as f:
            json.dump({"final_step": fr.global_step or (resumed or 0),
                       "pid": os.getpid()}, f)
    fr.exit_if_preempted()


if __name__ == "__main__":
    sys.exit(main())
