"""Regression tests for the round-1 advisor findings (ADVICE.md).

Oracles: torch (CPU build, baked into the image) for conv/ctc/pool semantics —
the same role numpy oracles play in the reference's OpTest (SURVEY.md §4).
"""
import numpy as np
import pytest
import torch

import paddle_tpu as pt
import paddle_tpu.nn.functional as F


# ---------------- autograd: multi-root backward in-degree ---------------------
def test_backward_multi_root_dependent_outputs():
    # z = y*y with y = 3x; backward([z, y]) must give dz/dx + dy/dx = (1 + 2*y)*3 = 21
    x = pt.to_tensor([1.0], stop_gradient=False)
    y = x * 3.0
    z = y * y
    pt.autograd.backward([z, y], [None, None])
    np.testing.assert_allclose(x.grad.numpy(), [21.0], rtol=1e-6)


def test_backward_multi_root_reverse_order():
    x = pt.to_tensor([1.0], stop_gradient=False)
    y = x * 3.0
    z = y * y
    pt.autograd.backward([y, z], [None, None])
    np.testing.assert_allclose(x.grad.numpy(), [21.0], rtol=1e-6)


def test_grad_does_not_pollute_other_leaves():
    x = pt.to_tensor([2.0], stop_gradient=False)
    w = pt.to_tensor([5.0], stop_gradient=False)
    y = w * x
    (gx,) = pt.grad(y, [x])
    np.testing.assert_allclose(gx.numpy(), [5.0])
    assert w.grad is None, ".grad of non-requested leaves must stay untouched"
    assert x.grad is None


# ---------------- conv_transpose ---------------------------------------------
@pytest.mark.parametrize("stride,padding,output_padding,dilation", [
    (1, 0, 0, 1),
    (2, 1, 0, 1),
    (2, 1, 1, 1),
    (1, 2, 0, 2),
    (3, 0, 2, 1),
])
def test_conv2d_transpose_matches_torch(stride, padding, output_padding,
                                        dilation):
    if output_padding >= max(stride, dilation):
        pytest.skip("invalid combination")
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 3, 5, 5)).astype(np.float32)
    w = rng.standard_normal((3, 4, 3, 3)).astype(np.float32)  # [in, out, kh, kw]
    b = rng.standard_normal((4,)).astype(np.float32)
    ref = torch.nn.functional.conv_transpose2d(
        torch.tensor(x), torch.tensor(w), torch.tensor(b), stride=stride,
        padding=padding, output_padding=output_padding, dilation=dilation)
    out = F.conv2d_transpose(pt.to_tensor(x), pt.to_tensor(w), pt.to_tensor(b),
                             stride=stride, padding=padding,
                             output_padding=output_padding, dilation=dilation)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-4, atol=1e-4)


def test_conv2d_transpose_default_expands():
    # ADVICE repro: k=3, s=1, p=0 must expand 5x5 -> 7x7 (was shrinking to 2x2)
    x = pt.ones([1, 1, 5, 5])
    w = pt.ones([1, 1, 3, 3])
    out = F.conv2d_transpose(x, w)
    assert out.shape == [1, 1, 7, 7]


def test_conv2d_transpose_groups():
    rng = np.random.default_rng(1)
    x = rng.standard_normal((1, 4, 6, 6)).astype(np.float32)
    w = rng.standard_normal((4, 3, 3, 3)).astype(np.float32)  # groups=2: out=6
    ref = torch.nn.functional.conv_transpose2d(
        torch.tensor(x), torch.tensor(w), stride=2, padding=1, groups=2)
    out = F.conv2d_transpose(pt.to_tensor(x), pt.to_tensor(w), stride=2,
                             padding=1, groups=2)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-4, atol=1e-4)


def test_conv1d_and_conv2d_match_torch():
    rng = np.random.default_rng(2)
    x = rng.standard_normal((2, 3, 9)).astype(np.float32)
    w = rng.standard_normal((5, 3, 3)).astype(np.float32)
    ref = torch.nn.functional.conv1d(torch.tensor(x), torch.tensor(w),
                                     stride=2, padding=1)
    out = F.conv1d(pt.to_tensor(x), pt.to_tensor(w), stride=2, padding=1)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-4, atol=1e-4)

    x2 = rng.standard_normal((2, 3, 8, 8)).astype(np.float32)
    w2 = rng.standard_normal((6, 1, 3, 3)).astype(np.float32)  # groups=3
    ref2 = torch.nn.functional.conv2d(torch.tensor(x2), torch.tensor(w2),
                                      padding=1, groups=3)
    out2 = F.conv2d(pt.to_tensor(x2), pt.to_tensor(w2), padding=1, groups=3)
    np.testing.assert_allclose(out2.numpy(), ref2.numpy(), rtol=1e-4, atol=1e-4)


# ---------------- ctc_loss ----------------------------------------------------
def test_ctc_loss_honors_input_lengths():
    rng = np.random.default_rng(3)
    T, B, C, L = 12, 3, 6, 4
    logits = rng.standard_normal((T, B, C)).astype(np.float32)
    log_probs = torch.log_softmax(torch.tensor(logits), dim=-1)
    labels = rng.integers(1, C, size=(B, L)).astype(np.int64)
    in_len = np.array([12, 7, 9], dtype=np.int64)
    lbl_len = np.array([4, 2, 3], dtype=np.int64)
    ref = torch.nn.functional.ctc_loss(
        log_probs, torch.tensor(labels), torch.tensor(in_len),
        torch.tensor(lbl_len), blank=0, reduction="none")
    got = F.ctc_loss(pt.to_tensor(log_probs.numpy()), pt.to_tensor(labels),
                     pt.to_tensor(in_len), pt.to_tensor(lbl_len), blank=0,
                     reduction="none")
    np.testing.assert_allclose(got.numpy(), ref.numpy(), rtol=1e-4, atol=1e-4)


# ---------------- dropout / pool ----------------------------------------------
def test_dropout_downscale_in_infer_eval_scales():
    x = pt.ones([4])
    out = F.dropout(x, p=0.5, training=False, mode="downscale_in_infer")
    np.testing.assert_allclose(out.numpy(), [0.5] * 4)
    out2 = F.dropout(x, p=0.5, training=False, mode="upscale_in_train")
    np.testing.assert_allclose(out2.numpy(), [1.0] * 4)


@pytest.mark.parametrize("ceil_mode", [False, True])
def test_max_pool2d_ceil_mode(ceil_mode):
    rng = np.random.default_rng(4)
    x = rng.standard_normal((1, 2, 7, 7)).astype(np.float32)
    ref = torch.nn.functional.max_pool2d(torch.tensor(x), 3, stride=2,
                                         padding=1, ceil_mode=ceil_mode)
    out = F.max_pool2d(pt.to_tensor(x), 3, stride=2, padding=1,
                       ceil_mode=ceil_mode)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5)


@pytest.mark.parametrize("ceil_mode", [False, True])
def test_avg_pool2d_ceil_mode(ceil_mode):
    rng = np.random.default_rng(5)
    x = rng.standard_normal((1, 2, 7, 7)).astype(np.float32)
    # paddle exclusive=True == torch count_include_pad=False
    ref = torch.nn.functional.avg_pool2d(
        torch.tensor(x), 3, stride=2, padding=1, ceil_mode=ceil_mode,
        count_include_pad=False)
    out = F.avg_pool2d(pt.to_tensor(x), 3, stride=2, padding=1,
                       ceil_mode=ceil_mode, exclusive=True)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5)


# ---------------- Tensor.to ---------------------------------------------------
def test_tensor_to_dtype_and_device():
    x = pt.to_tensor([1.0, 2.0])
    y = x.to("float16")
    assert y.dtype.name == "float16"
    z = x.to("cpu")
    assert z.place.startswith("cpu")
    with pytest.raises(ValueError):
        x.to("cuda")


def test_grad_wrt_intermediate_tensor():
    x = pt.to_tensor([2.0], stop_gradient=False)
    y = x * 3.0
    loss = y * y
    (gy,) = pt.grad(loss, [y])
    np.testing.assert_allclose(gy.numpy(), [12.0])  # 2*y = 12
    assert x.grad is None and y.grad is None


def test_tensor_to_dtype_aliases_and_grad_flow():
    t = pt.to_tensor([1.0])
    assert t.to("half").dtype.name == "float16"
    # .to(device) mid-graph must not detach the tape
    x = pt.to_tensor([2.0], stop_gradient=False)
    z = (x * 3.0).to("cpu")
    (z * z).backward()
    np.testing.assert_allclose(x.grad.numpy(), [36.0])


# ---------------- round-4 advisor findings ------------------------------------
def test_compiled_generate_cache_is_lru_capped(monkeypatch):
    """A serving loop over varying prompt lengths must not retain one
    executable per length forever (round-4 advisor finding)."""
    from paddle_tpu.models import generation
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    monkeypatch.setattr(generation, "_COMPILED_CACHE_CAP", 2)
    pt.seed(0)
    m = LlamaForCausalLM(LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=True))
    m.eval()
    rng = np.random.RandomState(0)
    for S in (4, 6, 8):
        ids = pt.to_tensor(rng.randint(0, 64, (1, S)).astype(np.int64))
        m.generate_compiled(ids, max_new_tokens=2, temperature=0.0)
    cache = m.__dict__["_compiled_generate"]
    assert len(cache) == 2
    # the oldest signature (prompt len 4) was evicted, newest retained
    lens = {sig[1] for sig in cache}
    assert lens == {6, 8}
    # a hit refreshes recency: touch len-6, add len-10, len-8 evicts
    ids6 = pt.to_tensor(rng.randint(0, 64, (1, 6)).astype(np.int64))
    m.generate_compiled(ids6, max_new_tokens=2, temperature=0.0)
    ids10 = pt.to_tensor(rng.randint(0, 64, (1, 10)).astype(np.int64))
    m.generate_compiled(ids10, max_new_tokens=2, temperature=0.0)
    assert {sig[1] for sig in cache} == {6, 10}


def test_autotune_measure_takes_min_of_two_slopes(monkeypatch):
    """One noisy timing window must not crown a winner that persists via
    PADDLE_AUTOTUNE_CACHE (round-4 advisor finding): _measure requires
    >=2 positive slopes and returns their min."""
    from paddle_tpu.ops.pallas import autotune as at

    times = iter([0.0, 1.0,            # warm window
                  0.0, 4.0, 4.0, 40.0,   # attempt 1: slope (36-4)/8 = 4.0
                  0.0, 8.0, 8.0, 32.0,   # attempt 2: slope (24-8)/8 = 2.0
                  ])
    monkeypatch.setattr(at.time, "perf_counter", lambda: next(times))
    got = at._measure(lambda: np.zeros(1), iters=4)
    assert got == pytest.approx(2.0)


def test_autotune_measure_rejects_unstable(monkeypatch):
    from paddle_tpu.ops.pallas import autotune as at

    # every window pair gives a non-positive slope -> unstable, raises
    vals = iter([0.0, 1.0] + [0.0, 5.0, 5.0, 6.0] * 4)
    monkeypatch.setattr(at.time, "perf_counter", lambda: next(vals))
    with pytest.raises(RuntimeError, match="unstable"):
        at._measure(lambda: np.zeros(1), iters=4)
