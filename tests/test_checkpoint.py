"""Distributed checkpointing subsystem (docs/CHECKPOINT.md): async sharded
save, atomic commit, integrity fallback, cross-mesh reshard, and the
fit-loop / TrainEpochRange / serving integration seams."""
import collections
import os
import subprocess
import sys
import threading
import time
import warnings

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.checkpoint import (CheckpointManager, CheckpointError,
                                   CheckpointIntegrityError, load_state_dir)
from paddle_tpu.checkpoint.layout import read_index
from paddle_tpu.checkpoint.writer import ckpt_metrics

Pair = collections.namedtuple("Pair", "first second")


def _state(seed=0, shape=(8, 16)):
    rng = np.random.RandomState(seed)
    return {
        "model": {"w": pt.to_tensor(rng.randn(*shape).astype(np.float32)),
                  "b": pt.to_tensor(rng.randn(shape[1]).astype(np.float32))},
        "optimizer": {"@step_count": 3,
                      "moments": Pair(pt.to_tensor([1.0, 2.0]), 0.9)},
        "names": ["a", "b"],
    }


def _assert_state_equal(a, b, exact=False):
    assert_eq = (np.testing.assert_array_equal if exact
                 else lambda x, y: np.testing.assert_allclose(x, y,
                                                              rtol=1e-7))
    assert_eq(a["model"]["w"].numpy(), b["model"]["w"].numpy())
    assert_eq(a["model"]["b"].numpy(), b["model"]["b"].numpy())
    assert a["optimizer"]["@step_count"] == b["optimizer"]["@step_count"]
    pa, pb = a["optimizer"]["moments"], b["optimizer"]["moments"]
    assert type(pa).__name__ == type(pb).__name__ == "Pair"
    assert_eq(pa.first.numpy(), pb.first.numpy())
    assert pa.second == pb.second
    assert a["names"] == b["names"]


class TestManagerBasics:
    def test_sync_roundtrip_preserves_structure(self, tmp_path):
        m = CheckpointManager(str(tmp_path), async_=False)
        st = _state()
        m.save(7, st, metadata={"epoch": 7})
        assert m.all_steps() == [7]
        assert m.latest_step() == 7
        assert m.metadata(7)["epoch"] == 7
        back = m.restore()
        _assert_state_equal(back, st, exact=True)
        assert m.last_restored_step == 7
        # marker + manifest + shards on disk, nothing half-written
        d = m.step_dir(7)
        assert os.path.isfile(os.path.join(d, "COMMITTED"))
        assert not any(n.endswith(".tmp") for n in os.listdir(tmp_path))

    def test_sharded_layout_under_topology(self, tmp_path):
        m = CheckpointManager(str(tmp_path), async_=False,
                              topology={"dp": 2, "mp": 2})
        m.save(0, _state())
        doc = read_index(m.step_dir(0))
        assert doc["topology"] == {"dp": 2, "mp": 2}
        grids = {tuple(e["grid"]) for e in doc["tensors"].values()}
        # the (8,16) weight must actually shard 4-ways on one dim
        assert (1, 4) in grids or (4, 1) in grids
        shard_files = [n for n in os.listdir(m.step_dir(0))
                       if n.endswith(".bin")]
        assert len(shard_files) > len(doc["tensors"])  # > 1 shard/tensor
        for e in doc["tensors"].values():
            for rec in e["shards"]:
                assert isinstance(rec["crc32"], int)

    def test_async_commit_ordering_and_in_flight(self, tmp_path):
        m = CheckpointManager(str(tmp_path), async_=True)
        futs = [m.save(s, _state(seed=s)) for s in range(4)]
        # the LAST future committing implies all earlier ones did (single
        # FIFO writer) — the async wait() ordering contract
        futs[-1].wait(120)
        assert all(f.done() for f in futs)
        assert m.all_steps() == [0, 1, 2, 3]
        m.wait_all()
        assert ckpt_metrics()["in_flight"].value() == 0.0
        back = m.restore(step=2)
        _assert_state_equal(back, _state(seed=2), exact=True)

    def test_keep_last_k_gc(self, tmp_path):
        m = CheckpointManager(str(tmp_path), keep_last_k=3, async_=False)
        for s in range(7):
            m.save(s, _state(seed=s))
        assert m.all_steps() == [4, 5, 6]
        assert not any(n == "step_0" for n in os.listdir(tmp_path))
        # restore still works on the survivors
        _assert_state_equal(m.restore(), _state(seed=6), exact=True)

    def test_gc_keeps_by_commit_recency_not_step_id(self, tmp_path):
        """A restarted run re-numbering from epoch 0 over a previous
        run's higher-id steps: GC must collect the STALE old steps, not
        the fresh low-id commits."""
        m = CheckpointManager(str(tmp_path), keep_last_k=2, async_=False)
        for s in (3, 4):
            m.save(s, _state(seed=s))
            # backdate the old run's commits so recency is unambiguous
            idx = os.path.join(m.step_dir(s), "index.json")
            os.utime(idx, (time.time() - 1000 + s, time.time() - 1000 + s))
        m.save(0, _state(seed=0), overwrite=True)  # the restart's epoch 0
        assert 0 in m.all_steps()          # fresh commit survived
        assert 3 not in m.all_steps()      # oldest stale step collected
        _assert_state_equal(m.restore(step=0), _state(seed=0), exact=True)

    def test_gc_spares_inflight_tmp_dirs(self, tmp_path):
        """The stale-.tmp sweep must only take dirs STRICTLY older than
        the newest commit — a live in-flight save (same or higher step,
        e.g. another rank's writer on a shared fs) is left alone."""
        m = CheckpointManager(str(tmp_path), async_=False)
        m.save(0, _state())
        os.makedirs(str(tmp_path / "step_0.tmp"))   # aborted residue
        os.makedirs(str(tmp_path / "step_5.tmp"))   # in-flight (newer)
        m.save(1, _state())                         # commit triggers GC
        assert not os.path.isdir(str(tmp_path / "step_0.tmp"))
        assert os.path.isdir(str(tmp_path / "step_5.tmp"))

    def test_restore_missing_raises(self, tmp_path):
        m = CheckpointManager(str(tmp_path))
        with pytest.raises(FileNotFoundError):
            m.restore()
        with pytest.raises(FileNotFoundError):
            m.restore(step=3)

    def test_duplicate_step_rejected(self, tmp_path):
        m = CheckpointManager(str(tmp_path), async_=False)
        m.save(1, _state())
        with pytest.raises(CheckpointError, match="already committed"):
            m.save(1, _state())

    def test_overwrite_replaces_step(self, tmp_path):
        m = CheckpointManager(str(tmp_path), async_=False)
        m.save(1, _state(seed=0))
        m.save(1, _state(seed=5), overwrite=True)
        assert m.all_steps() == [1]
        _assert_state_equal(m.restore(), _state(seed=5), exact=True)

    def test_bfloat16_roundtrip(self, tmp_path):
        """bf16 (the TPU default param dtype) must survive the shard
        format bit-exactly — .npy silently degraded it to raw void."""
        import jax.numpy as jnp
        w = jnp.arange(32, dtype=jnp.bfloat16).reshape(4, 8) / 7
        m = CheckpointManager(str(tmp_path), async_=False,
                              topology={"dp": 4})
        m.save(0, {"w": pt.Tensor(w)})
        back = m.restore()
        assert str(back["w"].data.dtype) == "bfloat16"
        np.testing.assert_array_equal(np.asarray(back["w"].data, np.float32),
                                      np.asarray(w, np.float32))

    def test_snapshot_isolated_from_later_updates(self, tmp_path):
        """Zero-copy snapshot correctness: mutating the live params (which
        REPLACES the immutable jax storage) after save() must not leak
        into the committed checkpoint; mutable numpy leaves are copied."""
        t = pt.to_tensor(np.ones((4, 4), np.float32))
        arr = np.full(3, 7, np.int64)
        m = CheckpointManager(str(tmp_path), async_=True,
                              fault_hook=lambda ph: time.sleep(0.05))
        fut = m.save(0, {"t": t, "a": arr})
        t.set_value(pt.to_tensor(np.zeros((4, 4), np.float32)))
        arr[:] = -1  # in-place numpy mutation after save returned
        fut.wait(120)
        back = m.restore()
        np.testing.assert_array_equal(back["t"].numpy(),
                                      np.ones((4, 4), np.float32))
        np.testing.assert_array_equal(back["a"], [7, 7, 7])

    def test_ckpt_metrics_exposed(self, tmp_path):
        from paddle_tpu.observability import get_registry
        m = CheckpointManager(str(tmp_path), async_=False)
        m.save(0, _state())
        m.restore()
        text = get_registry().prometheus_text()
        for fam in ("ckpt_save_seconds", "ckpt_blocking_seconds",
                    "ckpt_restore_seconds", "ckpt_bytes_total",
                    "ckpt_last_committed_step"):
            assert fam in text, fam
        assert ckpt_metrics()["last_step"].value() == 0.0


class TestCrashAndIntegrity:
    def test_crash_before_commit_never_loadable(self, tmp_path):
        """Killed between shard write and commit marker: the step must not
        be loadable; restore falls back to the surviving step, bit-
        identical — including under a changed mesh topology."""
        st0, st1 = _state(seed=0), _state(seed=1)
        m = CheckpointManager(str(tmp_path), async_=False,
                              topology={"dp": 8})
        m.save(0, st0)

        def die(phase):
            if phase == "before_commit":
                raise RuntimeError("simulated writer kill")

        m.fault_hook = die
        with pytest.raises(RuntimeError, match="simulated writer kill"):
            m.save(1, st1)
        # torn step: only a .tmp dir, invisible to every discovery surface
        assert m.all_steps() == [0]
        assert m.latest_step() == 0
        assert os.path.isdir(str(tmp_path / "step_1.tmp"))
        with pytest.raises((CheckpointError, FileNotFoundError)):
            load_state_dir(str(tmp_path / "step_1.tmp"))
        # fallback restore is bit-identical...
        _assert_state_equal(m.restore(), st0, exact=True)
        # ...including when re-laid onto a DIFFERENT mesh than it was
        # saved under (saved dp=8, restored dp=2 x mp=4)
        from paddle_tpu.distributed import init_mesh
        mesh_b = init_mesh({"dp": 2, "mp": 4})
        on_b = m.restore(mesh=mesh_b)
        _assert_state_equal(on_b, st0, exact=True)
        # a later save recovers and GC sweeps the torn residue
        m.fault_hook = None
        m.save(2, st1)
        assert m.all_steps() == [0, 2]
        assert not os.path.isdir(str(tmp_path / "step_1.tmp"))

    def test_crash_after_shards_same_guarantee(self, tmp_path):
        m = CheckpointManager(str(tmp_path), async_=False)
        m.save(0, _state(seed=0))

        def die(phase):
            if phase == "after_shards":
                raise RuntimeError("kill")

        m.fault_hook = die
        fut = m.save(1, _state(seed=1), async_=True)
        with pytest.raises(RuntimeError):
            fut.wait(120)
        assert m.latest_step() == 0

    def test_checksum_corruption_falls_back_loudly(self, tmp_path):
        st0, st1 = _state(seed=0), _state(seed=1)
        m = CheckpointManager(str(tmp_path), async_=False)
        m.save(0, st0)
        m.save(1, st1)
        # flip a byte in one of step 1's shards
        d = m.step_dir(1)
        shard = sorted(n for n in os.listdir(d) if n.endswith(".bin"))[0]
        p = os.path.join(d, shard)
        raw = bytearray(open(p, "rb").read())
        raw[-1] ^= 0xFF
        open(p, "wb").write(bytes(raw))

        before = ckpt_metrics()["failures"].value(kind="integrity")
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            back = m.restore()
        assert m.last_restored_step == 0
        _assert_state_equal(back, st0, exact=True)
        assert any("CORRUPT" in str(w.message) for w in caught)
        assert ckpt_metrics()["failures"].value(
            kind="integrity") == before + 1
        # an explicitly requested corrupt step raises instead of lying
        with pytest.raises(CheckpointIntegrityError):
            m.restore(step=1)

    def test_missing_shard_detected(self, tmp_path):
        m = CheckpointManager(str(tmp_path), async_=False)
        m.save(0, _state(seed=0))
        m.save(1, _state(seed=1))
        d = m.step_dir(1)
        os.unlink(os.path.join(
            d, sorted(n for n in os.listdir(d) if n.endswith(".bin"))[0]))
        with warnings.catch_warnings(record=True):
            warnings.simplefilter("always")
            m.restore()
        assert m.last_restored_step == 0


class TestReshard:
    def test_cross_mesh_parameter_equality(self, tmp_path):
        """Save under mesh A (dp=8), restore under mesh B (dp=2, mp=4):
        every parameter comes back bit-identical AND actually laid out on
        mesh B (elastic resume)."""
        from paddle_tpu.distributed import init_mesh
        mesh_a = init_mesh({"dp": 8})
        st = {"w": pt.to_tensor(
            np.random.RandomState(0).randn(16, 8).astype(np.float32))}
        m = CheckpointManager(str(tmp_path), async_=False)
        assert m.topology() == {"dp": 8}  # picked up from the live mesh
        m.save(0, st)
        doc = read_index(m.step_dir(0))
        assert doc["tensors"]["t0000"]["grid"] in ([8, 1], [1, 8])

        mesh_b = init_mesh({"dp": 2, "mp": 4})
        back = m.restore(mesh=mesh_b)
        np.testing.assert_array_equal(back["w"].numpy(), st["w"].numpy())
        sharding = back["w"].data.sharding
        assert sharding.mesh.shape == {"dp": 2, "mp": 4}
        # 16 divides 8 -> dim 0 is genuinely partitioned, not replicated
        assert not sharding.is_fully_replicated

    def test_ndarray_leaves_stay_numpy_under_mesh(self, tmp_path):
        """kind="ndarray" leaves restore as MUTABLE numpy even on the
        reshard path (jax arrays are immutable)."""
        from paddle_tpu.distributed import init_mesh
        st = {"rng_state": np.arange(8, dtype=np.int64),
              "w": pt.to_tensor(np.ones((8, 2), np.float32))}
        m = CheckpointManager(str(tmp_path), async_=False,
                              topology={"dp": 8})
        m.save(0, st)
        back = m.restore(mesh=init_mesh({"dp": 8}))
        assert isinstance(back["rng_state"], np.ndarray)
        back["rng_state"][0] = 99  # must not raise
        assert not isinstance(back["w"], np.ndarray)  # tensors placed

    def test_indivisible_shapes_replicate(self, tmp_path):
        from paddle_tpu.distributed import init_mesh
        st = {"odd": pt.to_tensor(np.arange(7, dtype=np.float32)),
              "scalar": pt.to_tensor(np.float32(3.5))}
        m = CheckpointManager(str(tmp_path), async_=False,
                              topology={"dp": 8})
        m.save(0, st)
        back = m.restore(mesh=init_mesh({"dp": 8}))
        np.testing.assert_array_equal(back["odd"].numpy(),
                                      np.arange(7, dtype=np.float32))
        assert float(back["scalar"].numpy()) == 3.5


class TestIoSatellites:
    def test_pdparams_namedtuple_preserved(self, tmp_path):
        obj = {"pair": Pair(pt.to_tensor([1.0]), 2), "x": 1}
        path = str(tmp_path / "nt.pdparams")
        pt.save(obj, path)
        back = pt.load(path)
        assert type(back["pair"]).__name__ == "Pair"
        assert back["pair"].second == 2
        np.testing.assert_array_equal(back["pair"].first.numpy(), [1.0])

    def test_paddle_load_dir_dispatch(self, tmp_path):
        m = CheckpointManager(str(tmp_path), async_=False)
        m.save(0, _state(seed=0))
        m.save(1, _state(seed=1))
        _assert_state_equal(pt.load(str(tmp_path)), _state(seed=1),
                            exact=True)  # root -> latest
        _assert_state_equal(pt.load(m.step_dir(0)), _state(seed=0),
                            exact=True)  # explicit step dir

    def test_paddle_load_non_checkpoint_dir_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="not a checkpoint"):
            pt.load(str(tmp_path))

    def test_nonzero_rank_save_blocks_until_commit(self, tmp_path,
                                                   monkeypatch):
        """Satellite: rank!=0 must not return from save() before rank 0's
        atomic publish is visible — otherwise it races ahead into load."""
        import jax
        from paddle_tpu.framework import io as fio
        path = str(tmp_path / "sync.pdparams")
        monkeypatch.setattr(jax, "process_index", lambda: 1)
        returned = threading.Event()

        t = threading.Thread(target=lambda: (fio.save({"a": 1}, path),
                                             returned.set()))
        t.start()
        time.sleep(0.15)
        assert not returned.is_set()  # still parked on the barrier
        monkeypatch.setattr(jax, "process_index", lambda: 0)
        fio.save({"a": 1}, path)  # "rank 0" publishes
        assert returned.wait(10)
        t.join()
        # RE-save to the SAME path: the barrier must key on the save
        # round, not bare file existence (which a stale file satisfies)
        monkeypatch.setattr(jax, "process_index", lambda: 1)
        returned2 = threading.Event()
        t2 = threading.Thread(target=lambda: (fio.save({"a": 2}, path),
                                              returned2.set()))
        t2.start()
        time.sleep(0.15)
        assert not returned2.is_set()  # old file must NOT satisfy round 2
        monkeypatch.setattr(jax, "process_index", lambda: 0)
        fio.save({"a": 2}, path)
        assert returned2.wait(10)
        t2.join()

    def test_nonzero_rank_barrier_times_out(self, tmp_path, monkeypatch):
        import jax
        from paddle_tpu.framework import io as fio
        monkeypatch.setattr(jax, "process_index", lambda: 1)
        monkeypatch.setenv("PADDLE_TPU_CKPT_BARRIER_TIMEOUT", "0.2")
        with pytest.raises(TimeoutError, match="no commit observed"):
            fio.save({"a": 1}, str(tmp_path / "never.pdparams"))


class TestTrainEpochRange:
    def test_atomic_model_opt_pair(self, tmp_path):
        """The torn-pair window: a crash mid-save must leave the LAST
        committed (model, opt) pair, never a mismatched one."""
        from paddle_tpu.incubate.checkpoint import TrainEpochRange
        pt.seed(0)
        m = nn.Linear(4, 2)
        opt = pt.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
        x = pt.to_tensor(np.ones((4, 4), np.float32))
        r = TrainEpochRange(3, str(tmp_path), model=m, optimizer=opt,
                            name="jobA")

        def die(phase):
            if phase == "before_commit" and r._mgr.latest_step() == 0:
                raise RuntimeError("killed mid-epoch-1-save")

        r._mgr.fault_hook = die
        w_after_epoch0 = None
        with pytest.raises(RuntimeError, match="killed"):
            for epoch in r:
                loss = pt.ops.mean(pt.ops.square(m(x)))
                loss.backward()
                opt.step()
                opt.clear_grad()
                if epoch == 0:
                    w_after_epoch0 = np.asarray(m.weight.data).copy()
        # resume: fresh objects restore the consistent epoch-0 pair
        pt.seed(99)
        m2 = nn.Linear(4, 2)
        opt2 = pt.optimizer.SGD(learning_rate=0.1,
                                parameters=m2.parameters())
        r2 = TrainEpochRange(3, str(tmp_path), model=m2, optimizer=opt2,
                             name="jobA")
        assert r2.restored_from == 1
        np.testing.assert_array_equal(np.asarray(m2.weight.data),
                                      w_after_epoch0)


class TestFitLoopIntegration:
    def _fit(self, tmp_path, async_, registry=None):
        from paddle_tpu.hapi.model import ModelCheckpoint
        rng = np.random.RandomState(0)
        X = rng.randn(32, 4).astype(np.float32)
        Y = (X @ rng.randn(4, 2)).astype(np.float32)
        pt.seed(1)
        net = nn.Linear(4, 2)
        model = pt.Model(net)
        model.prepare(optimizer=pt.optimizer.SGD(
            learning_rate=0.05, parameters=net.parameters()),
            loss=nn.MSELoss())
        cb = ModelCheckpoint(save_dir=str(tmp_path), async_=async_,
                             keep_last_k=2)
        # slow-disk injection: the write (NOT the snapshot) takes 0.25s —
        # an async save must not charge it to the fit loop
        mgr = cb.manager()
        mgr.fault_hook = lambda phase: (phase == "after_shards" and
                                        time.sleep(0.25))
        import paddle_tpu.io as io
        ds = io.TensorDataset([X, Y])
        model.fit(ds, batch_size=8, epochs=2, verbose=0, callbacks=[cb])
        return model, mgr

    def test_async_save_stalls_fit_loop_less_than_sync(self, tmp_path):
        """Acceptance criterion: the stall an epoch-end save injects into
        the fit loop (``ckpt_blocking_seconds``) is far smaller async
        than sync under the same (slow) disk."""
        hist = ckpt_metrics()["blocking_seconds"]

        def mean_blocking(mode, run):
            before = hist.stats(mode=mode) or {"sum": 0.0, "count": 0}
            run()
            after = hist.stats(mode=mode)
            n = after["count"] - before["count"]
            assert n >= 2  # one save per epoch reached the metric
            return (after["sum"] - before["sum"]) / n

        sync_mean = mean_blocking(
            "sync", lambda: self._fit(tmp_path / "sync", async_=False))
        async_mean = mean_blocking(
            "async", lambda: self._fit(tmp_path / "async", async_=True))
        assert sync_mean >= 0.25          # sync eats the full disk write
        assert async_mean < sync_mean / 5  # async pays ~only the snapshot

    def test_fit_drains_async_saves_on_mid_epoch_failure(self, tmp_path):
        """fit() must run on_train_end (ModelCheckpoint's wait_all) even
        when the loop dies mid-epoch — otherwise the last epoch's async
        save is lost on the daemon writer thread at process exit."""
        from paddle_tpu.hapi.model import Callback

        class Boom(Callback):
            def on_epoch_end(self, epoch, logs=None):
                if epoch == 1:
                    raise RuntimeError("mid-training failure")

        rng = np.random.RandomState(0)
        X = rng.randn(16, 4).astype(np.float32)
        Y = (X @ rng.randn(4, 2)).astype(np.float32)
        pt.seed(1)
        net = nn.Linear(4, 2)
        model = pt.Model(net)
        model.prepare(optimizer=pt.optimizer.SGD(
            learning_rate=0.05, parameters=net.parameters()),
            loss=nn.MSELoss())
        from paddle_tpu.hapi.model import ModelCheckpoint
        cb = ModelCheckpoint(save_dir=str(tmp_path), async_=True)
        import paddle_tpu.io as io
        with pytest.raises(RuntimeError, match="mid-training failure"):
            model.fit(io.TensorDataset([X, Y]), batch_size=8, epochs=3,
                      verbose=0, callbacks=[Boom(), cb])
        # epoch 0's save (submitted before the failure) was drained and
        # committed by the finally-path on_train_end
        assert cb.manager().all_steps() == [0]

    def test_model_load_flat_state_dict_dir(self, tmp_path):
        pt.seed(3)
        net = nn.Linear(4, 2)
        CheckpointManager(str(tmp_path), async_=False).save(
            0, net.state_dict())  # flat dict, no {"model": ...} wrapper
        pt.seed(55)
        net2 = nn.Linear(4, 2)
        pt.Model(net2).load(str(tmp_path))
        np.testing.assert_array_equal(np.asarray(net2.weight.data),
                                      np.asarray(net.weight.data))

    def test_fit_checkpoints_resumable_via_model_load(self, tmp_path):
        model, mgr = self._fit(tmp_path, async_=True)
        mgr.wait_all()
        assert mgr.all_steps() == [0, 1]
        pt.seed(33)
        net2 = nn.Linear(4, 2)
        model2 = pt.Model(net2)
        model2.prepare(optimizer=pt.optimizer.SGD(
            learning_rate=0.05, parameters=net2.parameters()),
            loss=nn.MSELoss())
        model2.load(str(tmp_path))  # dir-dispatch -> latest step
        np.testing.assert_array_equal(
            np.asarray(net2.weight.data),
            np.asarray(model.network.weight.data))


class TestServingWarmStart:
    def test_engine_load_weights_from_checkpoint_dir(self, tmp_path):
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.serving import ServingEngine
        cfg = LlamaConfig(vocab_size=64, hidden_size=16,
                          intermediate_size=32, num_hidden_layers=1,
                          num_attention_heads=2, num_key_value_heads=1,
                          max_position_embeddings=32,
                          tie_word_embeddings=True)
        pt.seed(0)
        stale = LlamaForCausalLM(cfg)
        pt.seed(7)
        trained = LlamaForCausalLM(cfg)
        mgr = CheckpointManager(str(tmp_path), async_=False)
        mgr.save(42, {"model": trained.state_dict(),
                      "optimizer": {"@step_count": 1}})

        engine = ServingEngine(stale, max_batch=2, max_blocks=8,
                               block_size=4, prefill_chunk=4)
        engine.load_weights(str(tmp_path))
        want = {k: np.asarray(v.data)
                for k, v in dict(trained.named_parameters()).items()}
        for name, arr in engine._st.items():
            if name in want:
                np.testing.assert_array_equal(np.asarray(arr), want[name])
        # ctor seam too
        engine2 = ServingEngine(LlamaForCausalLM(cfg),
                                warm_start_from=str(tmp_path),
                                max_batch=2, max_blocks=8, block_size=4,
                                prefill_chunk=4)
        np.testing.assert_array_equal(
            np.asarray(engine2._st["model.embed_tokens.weight"]),
            want["model.embed_tokens.weight"])


@pytest.mark.slow
class TestProcessKill:
    def test_real_process_kill_mid_save(self, tmp_path):
        """The literal crash: a child PROCESS os._exit()s between shard
        write and commit marker; the parent (a fresh reader, like a
        restarted trainer) must see only the surviving step."""
        code = f"""
import os, numpy as np
import paddle_tpu as pt
from paddle_tpu.checkpoint import CheckpointManager
root = {str(tmp_path)!r}
m = CheckpointManager(root, async_=False)
m.save(0, {{"w": pt.to_tensor(np.zeros(8, np.float32))}})
m.fault_hook = lambda phase: os._exit(9) if phase == "before_commit" \\
    else None
m.save(1, {{"w": pt.to_tensor(np.ones(8, np.float32))}})
"""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run([sys.executable, "-c", code], env=env,
                              capture_output=True, timeout=300)
        assert proc.returncode == 9, proc.stderr.decode()[-2000:]
        assert os.path.isdir(str(tmp_path / "step_1.tmp"))
        m = CheckpointManager(str(tmp_path))
        assert m.all_steps() == [0]
        back = m.restore()
        np.testing.assert_array_equal(back["w"].numpy(),
                                      np.zeros(8, np.float32))
