"""Flash-attention kernel tests (Pallas interpret mode on the CPU mesh) —
numeric parity vs the naive composite, forward and backward, causal and not,
plus tape integration through the Tensor API."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.ops.pallas.flash_attention import (flash_attention_bhsd,
                                                   flash_attention_bshd)


def naive(q, k, v, causal):
    s = jnp.einsum("bqd,bkd->bqk", q, k) / np.sqrt(q.shape[-1])
    if causal:
        m = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), bool))
        s = jnp.where(m, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v)


@pytest.fixture()
def qkv():
    rng = np.random.RandomState(0)
    BH, S, D = 3, 256, 64
    mk = lambda: jnp.asarray(rng.randn(BH, S, D), jnp.float32)
    return mk(), mk(), mk()


class TestForward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_parity(self, qkv, causal):
        q, k, v = qkv
        out = flash_attention_bhsd(q, k, v, causal=causal, block_q=64,
                                   block_k=64)
        ref = naive(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_uneven_blocks(self, qkv):
        q, k, v = qkv
        out = flash_attention_bhsd(q, k, v, causal=True, block_q=128,
                                   block_k=64)
        ref = naive(q, k, v, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_bhsd_4d(self, qkv):
        q, k, v = qkv
        q4 = q.reshape(1, 3, 256, 64)
        out = flash_attention_bhsd(q4, k.reshape(1, 3, 256, 64),
                                   v.reshape(1, 3, 256, 64), block_q=64,
                                   block_k=64)
        assert out.shape == (1, 3, 256, 64)

    def test_indivisible_seq_raises(self):
        q = jnp.zeros((1, 100, 64))
        with pytest.raises(ValueError):
            flash_attention_bhsd(q, q, q, block_q=64, block_k=64)

    def test_mismatched_kv_seq_raises(self):
        q = jnp.zeros((1, 128, 64))
        k = jnp.zeros((1, 256, 64))
        with pytest.raises(ValueError):
            flash_attention_bhsd(q, k, k, block_q=64, block_k=64)

    def test_sdpa_pallas_route_requires_maskless(self, monkeypatch):
        # the sdpa router must NOT take the pallas path when a mask or
        # active dropout is present (kernel implements neither); simulate a
        # TPU backend and record whether the kernel gets invoked
        import paddle_tpu as pt
        import paddle_tpu.nn.functional as F
        import paddle_tpu.ops.pallas.flash_attention as fa_mod
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        calls = []

        def fake_bshd(*a, **k):
            calls.append(1)
            raise RuntimeError("recorded")  # router falls back on error
        monkeypatch.setattr(fa_mod, "flash_attention_bshd", fake_bshd)

        rng = np.random.RandomState(0)
        B, S, H, D = 1, 64, 2, 32
        q = pt.to_tensor(rng.randn(B, S, H, D).astype(np.float32))
        mask = pt.to_tensor(np.zeros((B, H, S, S), np.float32))
        F.scaled_dot_product_attention(q, q, q, attn_mask=mask)
        assert not calls  # masked: composite path, kernel never touched
        F.scaled_dot_product_attention(q, q, q, dropout_p=0.5,
                                       training=True)
        assert not calls  # active dropout: composite path
        F.scaled_dot_product_attention(q, q, q, is_causal=True)
        assert calls  # eligible case reaches the kernel


class TestBackward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_naive(self, qkv, causal):
        q, k, v = qkv

        def f(a, b, c):
            return jnp.sum(jnp.sin(flash_attention_bhsd(
                a, b, c, causal=causal, block_q=64, block_k=64)))

        def g(a, b, c):
            return jnp.sum(jnp.sin(naive(a, b, c, causal)))

        got = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        ref = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
        for ga, ra in zip(got, ref):
            np.testing.assert_allclose(np.asarray(ga), np.asarray(ra),
                                       rtol=2e-3, atol=2e-4)


class TestTapeIntegration:
    def test_bshd_tensor_api_backward(self):
        rng = np.random.RandomState(1)
        B, S, H, D = 2, 128, 2, 32
        q = pt.to_tensor(rng.randn(B, S, H, D).astype(np.float32),
                         stop_gradient=False)
        k = pt.to_tensor(rng.randn(B, S, H, D).astype(np.float32),
                         stop_gradient=False)
        v = pt.to_tensor(rng.randn(B, S, H, D).astype(np.float32),
                         stop_gradient=False)
        out = flash_attention_bshd(q, k, v, causal=True, block_q=64,
                                   block_k=64)
        assert out.shape == [B, S, H, D]
        out.mean().backward()
        assert q.grad is not None and np.isfinite(q.grad.numpy()).all()
        assert k.grad is not None and v.grad is not None

        # matches the sdpa composite on the same Tensors
        import paddle_tpu.nn.functional as F
        ref = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=2e-4,
                                   atol=2e-5)
