"""Flash-attention kernel tests (Pallas interpret mode on the CPU mesh) —
numeric parity vs the naive composite, forward and backward, across the
kernel's full capability matrix: causal (with kv/q length offset), cross
attention, native GQA, segment ids (varlen/padding), streamed additive
bias, and tape integration through the Tensor API."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.ops.pallas.flash_attention import (flash_attention_bhsd,
                                                   flash_attention_bshd)

_NEG = -0.7 * float(np.finfo(np.float32).max)


def naive(q, k, v, causal=False, bias=None, qseg=None, kseg=None):
    """Oracle for [B, Hq, Sq, D] q with [B, Hkv, Sk, D] kv (GQA broadcast),
    mirroring the kernel's fully-masked-row → 0 convention."""
    if q.ndim == 3:
        q, k, v = q[:, None], k[:, None], v[:, None]
        squeeze = True
    else:
        squeeze = False
    B, Hq, Sq, D = q.shape
    Sk = k.shape[2]
    rep = Hq // k.shape[1]
    kf = jnp.repeat(k, rep, axis=1)
    vf = jnp.repeat(v, rep, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, kf) / np.sqrt(D)
    if bias is not None:
        s = s + bias
    live = jnp.ones((B, 1, Sq, Sk), bool)
    if qseg is not None:
        live = live & (qseg[:, None, :, None] == kseg[:, None, None, :])
    if causal:
        qi = jnp.arange(Sq)[:, None] + (Sk - Sq)
        live = live & (qi >= jnp.arange(Sk)[None, :])[None, None]
    s = jnp.where(live, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    if qseg is not None:
        p = jnp.where(live.any(-1, keepdims=True), p, 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vf)
    return out[:, 0] if squeeze else out


@pytest.fixture()
def qkv():
    rng = np.random.RandomState(0)
    BH, S, D = 3, 256, 64
    mk = lambda: jnp.asarray(rng.randn(BH, S, D), jnp.float32)
    return mk(), mk(), mk()


def rand4(rng, *shape):
    return jnp.asarray(rng.randn(*shape), jnp.float32)


class TestForward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_parity(self, qkv, causal):
        q, k, v = qkv
        out = flash_attention_bhsd(q, k, v, causal=causal, block_q=64,
                                   block_k=64)
        ref = naive(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_uneven_blocks(self, qkv):
        q, k, v = qkv
        out = flash_attention_bhsd(q, k, v, causal=True, block_q=128,
                                   block_k=64)
        ref = naive(q, k, v, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_bhsd_4d(self, qkv):
        q, k, v = qkv
        q4 = q.reshape(1, 3, 256, 64)
        out = flash_attention_bhsd(q4, k.reshape(1, 3, 256, 64),
                                   v.reshape(1, 3, 256, 64), block_q=64,
                                   block_k=64)
        assert out.shape == (1, 3, 256, 64)

    def test_indivisible_seq_raises(self):
        q = jnp.zeros((1, 100, 64))
        with pytest.raises(ValueError):
            flash_attention_bhsd(q, q, q, block_q=64, block_k=64)

    @pytest.mark.parametrize("causal", [False, True])
    def test_cross_attention(self, causal):
        # kv_len != q_len, reference flash_attn with differing seqlen_k
        rng = np.random.RandomState(3)
        q = rand4(rng, 2, 2, 128, 32)
        k = rand4(rng, 2, 2, 320, 32)
        v = rand4(rng, 2, 2, 320, 32)
        out = flash_attention_bhsd(q, k, v, causal=causal, block_q=64,
                                   block_k=64)
        ref = naive(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_decode_single_query(self):
        # Sq=1 against a long KV (the decode step shape)
        rng = np.random.RandomState(4)
        q = rand4(rng, 2, 4, 1, 32)
        k = rand4(rng, 2, 4, 256, 32)
        v = rand4(rng, 2, 4, 256, 32)
        out = flash_attention_bhsd(q, k, v, causal=True)
        ref = naive(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("hkv", [1, 2])
    def test_gqa(self, hkv):
        # KV heads < Q heads served by index maps, not replication
        rng = np.random.RandomState(5)
        q = rand4(rng, 2, 4, 128, 32)
        k = rand4(rng, 2, hkv, 128, 32)
        v = rand4(rng, 2, hkv, 128, 32)
        out = flash_attention_bhsd(q, k, v, causal=True, block_q=64,
                                   block_k=64)
        ref = naive(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_gqa_indivisible_heads_raises(self):
        q = jnp.zeros((1, 3, 64, 32))
        k = jnp.zeros((1, 2, 64, 32))
        with pytest.raises(ValueError):
            flash_attention_bhsd(q, k, k)

    def test_segment_ids(self):
        # two documents packed per row + padding tail (id 0 vs real ids)
        rng = np.random.RandomState(6)
        B, H, S, D = 2, 2, 256, 32
        q = rand4(rng, B, H, S, D)
        k = rand4(rng, B, H, S, D)
        v = rand4(rng, B, H, S, D)
        ids = np.where(np.arange(S) < 96, 1, np.where(np.arange(S) < 192,
                                                      2, 0))
        seg = jnp.asarray(np.stack([ids, ids]), jnp.int32)
        out = flash_attention_bhsd(q, k, v, q_segment_ids=seg,
                                   kv_segment_ids=seg, block_q=64,
                                   block_k=64)
        ref = naive(q, k, v, qseg=seg, kseg=seg)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_segment_fully_masked_rows_zero(self):
        # a query token whose id matches no kv token gets exactly 0 output
        rng = np.random.RandomState(7)
        q = rand4(rng, 1, 1, 128, 32)
        k = rand4(rng, 1, 1, 128, 32)
        v = rand4(rng, 1, 1, 128, 32)
        # boundary deliberately NOT tile-aligned (70 with block_q=64): dead
        # rows sharing tile j=1 with live rows must still emit exact 0
        qseg = jnp.asarray(np.where(np.arange(128) < 70, 1, 9)[None],
                           jnp.int32)
        kseg = jnp.asarray(np.ones((1, 128)), jnp.int32)
        out = np.asarray(flash_attention_bhsd(
            q, k, v, q_segment_ids=qseg, kv_segment_ids=kseg, block_q=64,
            block_k=64))
        assert np.all(out[0, 0, 70:] == 0.0)
        assert np.all(np.isfinite(out))
        # and their gradients are exactly 0 too
        def loss(a):
            o = flash_attention_bhsd(a, k, v, q_segment_ids=qseg,
                                     kv_segment_ids=kseg, block_q=64,
                                     block_k=64)
            return jnp.sum(o.astype(jnp.float32))
        dq = np.asarray(jax.grad(loss)(q))
        assert np.all(dq[0, 0, 70:] == 0.0) and np.all(np.isfinite(dq))

    @pytest.mark.parametrize("bshape", [(256, 256), (2, 1, 256, 256),
                                        (1, 2, 256, 256), (2, 2, 256, 256)])
    def test_bias_broadcast_shapes(self, bshape):
        rng = np.random.RandomState(8)
        q = rand4(rng, 2, 2, 256, 32)
        k = rand4(rng, 2, 2, 256, 32)
        v = rand4(rng, 2, 2, 256, 32)
        bias = jnp.asarray(rng.randn(*bshape) * 2, jnp.float32)
        out = flash_attention_bhsd(q, k, v, bias=bias, block_q=64,
                                   block_k=64)
        bias4 = bias if bias.ndim == 4 else bias[None, None]
        ref = naive(q, k, v, bias=bias4)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_bias_key_padding_row_broadcast(self):
        # [B, 1, 1, Sk] key-padding mask: streamed via a one-row BlockSpec,
        # never broadcast to Sq in HBM
        rng = np.random.RandomState(10)
        q = rand4(rng, 2, 2, 128, 32)
        k = rand4(rng, 2, 2, 128, 32)
        v = rand4(rng, 2, 2, 128, 32)
        pad = np.zeros((2, 1, 1, 128), np.float32)
        pad[:, :, :, 96:] = np.finfo(np.float32).min
        bias = jnp.asarray(pad)
        out = flash_attention_bhsd(q, k, v, bias=bias, block_q=64,
                                   block_k=64)
        ref = naive(q, k, v, bias=bias)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_bias_as_additive_causal_mask(self):
        # an explicit -inf-style additive mask matches the causal flag
        rng = np.random.RandomState(9)
        q = rand4(rng, 1, 2, 128, 32)
        k = rand4(rng, 1, 2, 128, 32)
        v = rand4(rng, 1, 2, 128, 32)
        mask = jnp.where(jnp.tril(jnp.ones((128, 128), bool)), 0.0,
                         jnp.finfo(jnp.float32).min)
        out = flash_attention_bhsd(q, k, v, bias=mask, block_q=64,
                                   block_k=64)
        ref = flash_attention_bhsd(q, k, v, causal=True, block_q=64,
                                   block_k=64)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_sdpa_router(self, monkeypatch):
        # masked, GQA and DROPOUT cases all ROUTE to the kernel now
        import paddle_tpu.nn.functional as F
        import paddle_tpu.ops.pallas.flash_attention as fa_mod
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        calls = []

        def fake_bshd(*a, **kw):
            calls.append(kw)
            raise RuntimeError("recorded")  # router falls back on error
        monkeypatch.setattr(fa_mod, "flash_attention_bshd", fake_bshd)

        rng = np.random.RandomState(0)
        B, S, H, D = 1, 64, 2, 32
        q = pt.to_tensor(rng.randn(B, S, H, D).astype(np.float32))
        mask = pt.to_tensor(np.zeros((B, H, S, S), np.float32))
        F.scaled_dot_product_attention(q, q, q, attn_mask=mask)
        assert len(calls) == 1 and calls[0]["bias"] is not None
        F.scaled_dot_product_attention(q, q, q, dropout_p=0.5,
                                       training=True)
        # active dropout reaches the kernel WITH p and a seed
        assert len(calls) == 2 and calls[1]["dropout_p"] == 0.5
        assert calls[1]["dropout_seed"] is not None
        F.scaled_dot_product_attention(q, q, q, dropout_p=0.5,
                                       training=False)
        assert calls[2]["dropout_p"] == 0.0  # eval: dropout off
        F.scaled_dot_product_attention(q, q, q, is_causal=True)
        assert len(calls) == 4  # plain causal reaches the kernel

        # generate_square_subsequent_mask is recognized: kernel sees
        # causal=True and NO bias (S×S mask never streamed)
        from paddle_tpu.nn.layer.transformer import Transformer
        cm = Transformer.generate_square_subsequent_mask(S)
        F.scaled_dot_product_attention(q, q, q, attn_mask=cm)
        assert calls[-1].get("bias") is None
        # composite fallback with the same tagged mask matches causal
        monkeypatch.undo()
        got = F.scaled_dot_product_attention(q, q, q, attn_mask=cm)
        want = F.scaled_dot_product_attention(q, q, q, is_causal=True)
        np.testing.assert_allclose(got.numpy(), want.numpy(), rtol=2e-4,
                                   atol=2e-5)


class TestBackward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_naive(self, qkv, causal):
        q, k, v = qkv

        def f(a, b, c):
            return jnp.sum(jnp.sin(flash_attention_bhsd(
                a, b, c, causal=causal, block_q=64, block_k=64)))

        def g(a, b, c):
            return jnp.sum(jnp.sin(naive(a, b, c, causal)))

        got = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        ref = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
        for ga, ra in zip(got, ref):
            np.testing.assert_allclose(np.asarray(ga), np.asarray(ra),
                                       rtol=2e-3, atol=2e-4)

    def test_grads_gqa_cross_causal(self):
        rng = np.random.RandomState(11)
        q = rand4(rng, 2, 4, 128, 32)
        k = rand4(rng, 2, 2, 256, 32)
        v = rand4(rng, 2, 2, 256, 32)

        def f(a, b, c):
            return jnp.sum(jnp.sin(flash_attention_bhsd(
                a, b, c, causal=True, block_q=64, block_k=64)))

        def g(a, b, c):
            return jnp.sum(jnp.sin(naive(a, b, c, causal=True)))

        got = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        ref = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
        assert got[1].shape == k.shape  # dk at KV-head resolution
        for ga, ra in zip(got, ref):
            np.testing.assert_allclose(np.asarray(ga), np.asarray(ra),
                                       rtol=2e-3, atol=2e-4)

    def test_grads_segments_bias(self):
        rng = np.random.RandomState(12)
        B, H, S, D = 2, 2, 128, 32
        q = rand4(rng, B, H, S, D)
        k = rand4(rng, B, H, S, D)
        v = rand4(rng, B, H, S, D)
        bias = jnp.asarray(rng.randn(1, H, S, S), jnp.float32)
        ids = np.where(np.arange(S) < 96, 1, 0)
        seg = jnp.asarray(np.stack([ids, ids]), jnp.int32)

        def f(a, b, c):
            return jnp.sum(jnp.sin(flash_attention_bhsd(
                a, b, c, bias=bias, q_segment_ids=seg, kv_segment_ids=seg,
                block_q=64, block_k=64)))

        def g(a, b, c):
            return jnp.sum(jnp.sin(naive(a, b, c, bias=bias, qseg=seg,
                                         kseg=seg)))

        got = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        ref = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
        for ga, ra in zip(got, ref):
            np.testing.assert_allclose(np.asarray(ga), np.asarray(ra),
                                       rtol=2e-3, atol=2e-4)
            assert np.all(np.isfinite(np.asarray(ga)))


class TestTapeIntegration:
    def test_bshd_tensor_api_backward(self):
        rng = np.random.RandomState(1)
        B, S, H, D = 2, 128, 2, 32
        q = pt.to_tensor(rng.randn(B, S, H, D).astype(np.float32),
                         stop_gradient=False)
        k = pt.to_tensor(rng.randn(B, S, H, D).astype(np.float32),
                         stop_gradient=False)
        v = pt.to_tensor(rng.randn(B, S, H, D).astype(np.float32),
                         stop_gradient=False)
        out = flash_attention_bshd(q, k, v, causal=True, block_q=64,
                                   block_k=64)
        assert out.shape == [B, S, H, D]
        out.mean().backward()
        assert q.grad is not None and np.isfinite(q.grad.numpy()).all()
        assert k.grad is not None and v.grad is not None

        # matches the sdpa composite on the same Tensors
        import paddle_tpu.nn.functional as F
        ref = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=2e-4,
                                   atol=2e-5)

    def test_gqa_functional_flash(self):
        # F.flash_attention accepts GQA-shaped kv in paddle layout
        import paddle_tpu.nn.functional as F
        rng = np.random.RandomState(2)
        B, S, H, Hkv, D = 2, 128, 4, 2, 32
        q = pt.to_tensor(rng.randn(B, S, H, D).astype(np.float32),
                         stop_gradient=False)
        k = pt.to_tensor(rng.randn(B, S, Hkv, D).astype(np.float32),
                         stop_gradient=False)
        v = pt.to_tensor(rng.randn(B, S, Hkv, D).astype(np.float32),
                         stop_gradient=False)
        out = F.flash_attention(q, k, v, causal=True)
        ref = naive(jnp.swapaxes(q.data, 1, 2), jnp.swapaxes(k.data, 1, 2),
                    jnp.swapaxes(v.data, 1, 2), causal=True)
        np.testing.assert_allclose(out.numpy(),
                                   np.asarray(jnp.swapaxes(ref, 1, 2)),
                                   rtol=2e-4, atol=2e-5)
        out.mean().backward()
        assert k.grad.shape == [B, S, Hkv, D]

    def test_segment_ids_through_functional(self):
        import paddle_tpu.nn.functional as F
        rng = np.random.RandomState(3)
        B, S, H, D = 2, 128, 2, 32
        q = pt.to_tensor(rng.randn(B, S, H, D).astype(np.float32))
        seg = pt.to_tensor(
            np.where(np.arange(S) < 64, 1, 0)[None].repeat(B, 0)
            .astype(np.int32))
        out = F.flash_attention(q, q, q, q_segment_ids=seg,
                                kv_segment_ids=seg)
        ref = naive(jnp.swapaxes(q.data, 1, 2), jnp.swapaxes(q.data, 1, 2),
                    jnp.swapaxes(q.data, 1, 2), qseg=seg.data,
                    kseg=seg.data)
        np.testing.assert_allclose(out.numpy(),
                                   np.asarray(jnp.swapaxes(ref, 1, 2)),
                                   rtol=2e-4, atol=2e-5)


class TestDropout:
    """In-kernel attention dropout: position-hashed keep mask (identical
    in fwd and both bwd kernels), l keeps the raw softmax denominator —
    standard post-softmax dropout semantics."""

    @staticmethod
    def np_keep(seed, bh, Sq, Sk, p):
        """numpy reimplementation of the kernel's murmur-style hash
        (int64 arithmetic masked to 32 bits: identical wrap semantics,
        no numpy scalar-overflow warnings)."""
        M = 0xFFFFFFFF
        qi, ki = np.meshgrid(np.arange(Sq, dtype=np.int64),
                             np.arange(Sk, dtype=np.int64), indexing="ij")
        x = (qi * 0x9E3779B9) & M
        x ^= (ki * 0xC2B2AE35) & M
        x ^= (int(bh) * 0x85EBCA6B) & M
        x ^= np.int64(np.uint32(np.int32(seed)))
        x ^= x >> 16
        x = (x * 0x85EBCA6B) & M
        x ^= x >> 13
        x = (x * 0xC2B2AE35) & M
        x ^= x >> 16
        thr = min(int(p * 2**32), 2**32 - 1)
        return x >= thr

    def oracle_dropout(self, q, k, v, p, seed):
        """Standard attention with the kernel's exact mask."""
        BH, S, D = q.shape
        s = np.einsum("bqd,bkd->bqk", np.asarray(q), np.asarray(k)) / \
            np.sqrt(D)
        w = np.exp(s - s.max(-1, keepdims=True))
        w = w / w.sum(-1, keepdims=True)
        out = np.zeros_like(np.asarray(q))
        for bh in range(BH):
            keep = self.np_keep(seed, bh, S, S, p)
            wd = np.where(keep, w[bh], 0.0) / (1.0 - p)
            out[bh] = wd @ np.asarray(v[bh])
        return out

    def test_p0_matches_plain(self, qkv):
        q, k, v = qkv
        a = flash_attention_bhsd(q, k, v, block_q=64, block_k=64)
        b = flash_attention_bhsd(q, k, v, dropout_p=0.0, block_q=64,
                                 block_k=64)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_matches_hash_oracle_exactly(self, qkv):
        q, k, v = qkv
        p, seed = 0.3, 1234
        out = flash_attention_bhsd(q, k, v, dropout_p=p, dropout_seed=seed,
                                   block_q=64, block_k=64)
        ref = self.oracle_dropout(q, k, v, p, seed)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4,
                                   atol=2e-5)

    def test_deterministic_and_seed_sensitive(self, qkv):
        q, k, v = qkv
        a = flash_attention_bhsd(q, k, v, dropout_p=0.2, dropout_seed=7,
                                 block_q=64, block_k=64)
        b = flash_attention_bhsd(q, k, v, dropout_p=0.2, dropout_seed=7,
                                 block_q=64, block_k=64)
        c = flash_attention_bhsd(q, k, v, dropout_p=0.2, dropout_seed=8,
                                 block_q=64, block_k=64)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert np.abs(np.asarray(a) - np.asarray(c)).max() > 0

    def test_block_size_invariant(self, qkv):
        # the mask is position-based: tiling must not change the result
        q, k, v = qkv
        a = flash_attention_bhsd(q, k, v, dropout_p=0.25, dropout_seed=3,
                                 block_q=64, block_k=64)
        b = flash_attention_bhsd(q, k, v, dropout_p=0.25, dropout_seed=3,
                                 block_q=128, block_k=64)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)

    def test_grads_match_hash_oracle(self):
        rng = np.random.RandomState(13)
        BH, S, D = 2, 128, 32
        q = jnp.asarray(rng.randn(BH, S, D), jnp.float32)
        k = jnp.asarray(rng.randn(BH, S, D), jnp.float32)
        v = jnp.asarray(rng.randn(BH, S, D), jnp.float32)
        p, seed = 0.3, 99

        keeps = np.stack([self.np_keep(seed, bh, S, S, p)
                          for bh in range(BH)])

        def ref(a, b, c):
            s = jnp.einsum("bqd,bkd->bqk", a, b) / np.sqrt(D)
            w = jax.nn.softmax(s, axis=-1)
            wd = jnp.where(jnp.asarray(keeps), w, 0.0) / (1.0 - p)
            return jnp.sum(jnp.sin(jnp.einsum("bqk,bkd->bqd", wd, c)))

        def got(a, b, c):
            return jnp.sum(jnp.sin(flash_attention_bhsd(
                a, b, c, dropout_p=p, dropout_seed=seed, block_q=64,
                block_k=64)))

        ga = jax.grad(got, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
        for x, y in zip(ga, gr):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=2e-3, atol=2e-4)

    def test_grads_gqa_causal_dropout(self):
        """The riskiest path: dkv's _qflat-derived head index must give
        the SAME mask the forward used, under GQA + causal."""
        rng = np.random.RandomState(21)
        B, Hq, Hkv, S, D = 2, 4, 2, 128, 32
        q = jnp.asarray(rng.randn(B, Hq, S, D), jnp.float32)
        k = jnp.asarray(rng.randn(B, Hkv, S, D), jnp.float32)
        v = jnp.asarray(rng.randn(B, Hkv, S, D), jnp.float32)
        p, seed = 0.25, 17
        keeps = np.stack([self.np_keep(seed, bh, S, S, p)
                          for bh in range(B * Hq)]).reshape(B, Hq, S, S)

        def ref(a, b, c):
            G = Hq // Hkv
            kf = jnp.repeat(b, G, axis=1)
            vf = jnp.repeat(c, G, axis=1)
            s_ = jnp.einsum("bhqd,bhkd->bhqk", a, kf) / np.sqrt(D)
            causal = jnp.tril(jnp.ones((S, S), bool))
            s_ = jnp.where(causal, s_, -jnp.inf)
            w = jax.nn.softmax(s_, axis=-1)
            wd = jnp.where(jnp.asarray(keeps), w, 0.0) / (1.0 - p)
            return jnp.sum(jnp.sin(jnp.einsum("bhqk,bhkd->bhqd", wd, vf)))

        def got(a, b, c):
            return jnp.sum(jnp.sin(flash_attention_bhsd(
                a, b, c, causal=True, dropout_p=p, dropout_seed=seed,
                block_q=64, block_k=64)))

        ga = jax.grad(got, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
        for x, y in zip(ga, gr):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=2e-3, atol=2e-4)

    def test_drop_rate(self):
        keep = self.np_keep(5, 0, 256, 256, 0.4)
        rate = 1.0 - keep.mean()
        assert abs(rate - 0.4) < 0.01, rate


class TestTrainableMask:
    def test_trainable_additive_mask_gets_grad(self):
        """A learned additive bias (stop_gradient=False float mask) must
        RECEIVE a gradient — the reference's composite adds the mask to the
        logits; its fused kernel emits grad_bias. Constant masks stay
        zero-grad constants on every route."""
        import paddle_tpu as pt
        from paddle_tpu.nn import functional as F

        rng = np.random.RandomState(0)
        q = pt.to_tensor(rng.randn(1, 8, 2, 16).astype(np.float32),
                         stop_gradient=False)
        bias = pt.to_tensor(np.zeros((1, 1, 8, 8), np.float32),
                            stop_gradient=False)
        out = F.scaled_dot_product_attention(q, q, q, attn_mask=bias)
        out.mean().backward()
        assert bias.grad is not None
        g = bias.grad.numpy()
        assert np.isfinite(g).all() and np.abs(g).max() > 0
        # softmax-row structure: per-(row) bias grads sum to ~0 (shift
        # invariance of softmax under the mean loss chain rule is broken
        # by V, so just check the value route actually differentiated)
        q2 = pt.to_tensor(q.numpy(), stop_gradient=False)
        const = pt.to_tensor(np.ones((1, 1, 8, 8), np.float32) * 0.3)
        out2 = F.scaled_dot_product_attention(q2, q2, q2, attn_mask=const)
        out3 = F.scaled_dot_product_attention(
            q2, q2, q2,
            attn_mask=pt.to_tensor(np.ones((1, 1, 8, 8), np.float32) * 0.3,
                                   stop_gradient=False))
        np.testing.assert_allclose(out2.numpy(), out3.numpy(), atol=1e-6)
