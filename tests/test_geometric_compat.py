"""paddle.geometric + compat shims (batch/reader/callbacks/hub/
sysconfig/onnx/version)."""
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import geometric as G


def _t(x):
    return pt.to_tensor(np.asarray(x))


# -------------------------------------------------------------- geometric
def test_segment_reductions_vs_numpy():
    data = np.array([[1., 2.], [3., 4.], [5., 6.], [7., 8.]], np.float32)
    ids = np.array([0, 0, 1, 1], np.int64)
    np.testing.assert_allclose(
        np.asarray(G.segment_sum(_t(data), _t(ids)).data),
        [[4., 6.], [12., 14.]], rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(G.segment_mean(_t(data), _t(ids)).data),
        [[2., 3.], [6., 7.]], rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(G.segment_max(_t(data), _t(ids)).data),
        [[3., 4.], [7., 8.]], rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(G.segment_min(_t(data), _t(ids)).data),
        [[1., 2.], [5., 6.]], rtol=1e-6)


def test_segment_empty_segment_fills_zero():
    data = np.array([[1.0], [2.0]], np.float32)
    ids = np.array([0, 2], np.int64)  # segment 1 untouched
    out = np.asarray(G.segment_max(_t(data), _t(ids)).data)
    np.testing.assert_allclose(out, [[1.0], [0.0], [2.0]], rtol=1e-6)


def test_send_u_recv_matches_manual():
    x = np.array([[1., 1.], [2., 2.], [3., 3.]], np.float32)
    src = np.array([0, 1, 2, 0], np.int64)
    dst = np.array([1, 2, 1, 0], np.int64)
    out = np.asarray(G.send_u_recv(_t(x), _t(src), _t(dst),
                                   reduce_op="sum").data)
    # dst 0 <- x[0]; dst 1 <- x[0]+x[2]; dst 2 <- x[1]
    np.testing.assert_allclose(out, [[1., 1.], [4., 4.], [2., 2.]],
                               rtol=1e-6)


def test_send_ue_recv_and_send_uv():
    x = np.array([[1.], [2.]], np.float32)
    e = np.array([[10.], [20.]], np.float32)
    src = np.array([0, 1], np.int64)
    dst = np.array([1, 0], np.int64)
    out = np.asarray(G.send_ue_recv(_t(x), _t(e), _t(src), _t(dst),
                                    message_op="add").data)
    np.testing.assert_allclose(out, [[22.], [11.]], rtol=1e-6)
    uv = np.asarray(G.send_uv(_t(x), _t(x), _t(src), _t(dst),
                              message_op="mul").data)
    np.testing.assert_allclose(uv, [[2.], [2.]], rtol=1e-6)


def test_segment_sum_gradient():
    data = _t(np.ones((4, 2), np.float32))
    data.stop_gradient = False
    ids = _t(np.array([0, 0, 1, 1], np.int64))
    out = G.segment_sum(data, ids)
    pt.ops.sum(pt.ops.multiply(out, out)).backward()
    # d/dx sum((sum_seg x)^2) = 2 * seg_total broadcast back
    np.testing.assert_allclose(np.asarray(data.grad.data),
                               4 * np.ones((4, 2)), rtol=1e-5)


# ------------------------------------------------------------------ compat
def test_batch_and_reader_decorators():
    def samples():
        yield from range(10)

    batches = list(pt.batch(samples, 3)())
    assert batches == [[0, 1, 2], [3, 4, 5], [6, 7, 8], [9]]
    assert list(pt.batch(samples, 3, drop_last=True)()) == [
        [0, 1, 2], [3, 4, 5], [6, 7, 8]]

    from paddle_tpu import reader
    doubled = reader.map_readers(lambda a: a * 2, samples)
    assert list(doubled())[:3] == [0, 2, 4]
    assert sorted(reader.shuffle(samples, 4)()) == list(range(10))
    assert list(reader.firstn(samples, 3)()) == [0, 1, 2]
    assert list(reader.buffered(samples, 2)()) == list(range(10))
    assert list(reader.chain(samples, samples)()) == \
        list(range(10)) * 2


def test_callbacks_namespace():
    assert pt.callbacks.EarlyStopping is not None
    assert pt.callbacks.ModelCheckpoint is not None


def test_hub_local(tmp_path):
    conf = os.path.join(tmp_path, "hubconf.py")
    with open(conf, "w") as f:
        f.write("def tiny_model(scale=1):\n"
                "    'a tiny model'\n"
                "    return {'scale': scale}\n")
    assert pt.hub.list(str(tmp_path)) == ["tiny_model"]
    assert "tiny" in pt.hub.help(str(tmp_path), "tiny_model")
    assert pt.hub.load(str(tmp_path), "tiny_model", scale=3) == {"scale": 3}
    with pytest.raises(RuntimeError):
        pt.hub.load("owner/repo", "m", source="github")


def test_sysconfig_paths_exist():
    inc = pt.sysconfig.get_include()
    assert os.path.exists(os.path.join(inc, "paddle_tpu_ext.h"))
    assert os.path.basename(pt.sysconfig.get_lib()) == "build"


def test_onnx_export_raises_with_guidance():
    with pytest.raises(NotImplementedError, match="jit.save"):
        pt.onnx.export(None, "/tmp/x")


def test_version():
    assert pt.version.full_version.startswith("2.5")
    assert pt.version.cuda() == "False"


def test_compose_alignment_semantics():
    from paddle_tpu import reader

    def r5():
        yield from range(5)

    def r3():
        yield from range(3)

    with pytest.raises(reader.ComposeNotAligned):
        list(reader.compose(r5, r3)())
    # check_alignment=False truncates to the shortest
    assert list(reader.compose(r5, r3, check_alignment=False)()) == [
        (0, 0), (1, 1), (2, 2)]
